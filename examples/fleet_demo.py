"""Fleet-scale walkthrough: the product configuration, end to end.

The reference runs 7 oracles in a Python loop; this framework's pitch
is a 1024-oracle fleet on TPU.  This demo drives that configuration on
any backend (tiny encoder so CPU finishes in ~a minute):

1. comments → sequence-packed sentiment (flash segment-tag attention) →
   vmapped bootstrap fleet → fused two-pass consensus, the flagship
   device path (``bench.py --config 12`` measures it for real);
2. the same fleet committed THROUGH THE CHAIN ADAPTER — 1024 signed-tx
   semantics in one device-certified batched commit
   (:mod:`svoc_tpu.consensus.batch`), then ``resume`` reads the
   contract back;
3. detection quality at fleet scale: a mini acceptance row (uniform
   adversaries) and a mini breakdown row (coordinated 55 % bias — the
   capture regime documented in ``docs/ALGORITHM.md`` §5).

Usage::

    python examples/fleet_demo.py [--oracles 1024] [--failing 256]
        [--trials 50] [--platform cpu]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--oracles", type=int, default=1024)
    p.add_argument("--failing", type=int, default=256)
    p.add_argument("--trials", type=int, default=50)
    p.add_argument(
        "--platform",
        default="cpu",
        help=(
            "JAX platform; 'cpu' (default) pins the CPU backend BEFORE "
            "device init so a wedged accelerator plugin cannot hang the "
            "demo; pass 'default' to use the ambient backend"
        ),
    )
    args = p.parse_args()
    if args.platform != "default":
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    if args.platform != "default":
        jax.config.update("jax_platforms", args.platform)
    import numpy as np
    from dataclasses import replace

    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource
    from svoc_tpu.models.configs import TINY_TEST
    from svoc_tpu.models.sentiment import SentimentPipeline
    from svoc_tpu.sim.montecarlo import fleet_benchmark

    n, f = args.oracles, args.failing

    # -- 1. the device path: packed x flash sentiment → fleet → consensus
    print(f"== fleet demo: {n} oracles, {f} adversarial ==")
    pipe = SentimentPipeline(
        cfg=replace(TINY_TEST, attention="flash"),
        seq_len=32,
        batch_size=16,
        tokenizer_name=None,
        packed=True,
    )
    store = CommentStore()
    store.save(SyntheticSource(batch=60, seed=1)())
    session = Session(
        config=SessionConfig(n_oracles=n, n_failing=f),
        store=store,
        vectorizer=pipe,
    )
    t0 = time.perf_counter()
    preview = session.fetch()
    t_fetch = time.perf_counter() - t0
    print(
        f"fetch: {preview['n_comments']} comments -> {n} oracle "
        f"predictions in {t_fetch:.2f}s (packed x flash forward + "
        "bootstrap fleet + preview ranks)"
    )
    suspects = int(np.sum(preview["normalized_ranks"] <= 0.2))
    print(f"preview flags {suspects} oracles as suspect (red in the UI)")

    # -- 2. fleet-scale commit through the chain adapter (batched path)
    t0 = time.perf_counter()
    n_tx = session.commit()
    t_commit = time.perf_counter() - t0
    state = session.adapter.resume()
    print(
        f"commit: {n_tx} txs in {t_commit:.2f}s (device-certified batch "
        "— sequential-loop semantics, O(1) golden recomputes)"
    )
    print(
        f"on-chain: active={state['consensus_active']} rel1="
        f"{state['reliability_first_pass']:.3f} rel2="
        f"{state['reliability_second_pass']:.3f}"
    )

    # -- 3. detection quality at this scale
    key = jax.random.PRNGKey(0)
    r = fleet_benchmark(key, n, f, k_trials=args.trials)
    print(
        f"uniform adversaries ({f}/{n}): per-oracle misflag rate "
        f"{r['misclassified_rate_pct']:.2f} %, restricted-median "
        f"reliability {r['reliability_pct']:.2f} %"
    )
    f_capture = int(0.55 * n)
    r = fleet_benchmark(key, n, f_capture, k_trials=args.trials, biased=True)
    print(
        f"coordinated capture ({f_capture}/{n}, biased): misflag rate "
        f"{r['misclassified_rate_pct']:.2f} % — the estimator inverts "
        "past N/2 (docs/ALGORITHM.md §5 breakdown curve)"
    )


if __name__ == "__main__":
    main()
