"""Saturation bench for the continuous-batching serving tier.

Open-loop load against :class:`svoc_tpu.serving.tier.ServingTier`
(docs/SERVING.md §bench): each offered-QPS level gets a FRESH seeded
run — fresh :class:`~svoc_tpu.utils.events.EventJournal`, fresh
:class:`~svoc_tpu.utils.metrics.MetricsRegistry`, pinned lineage scope,
virtual clock (the PR 6 replay-pinning rules) — and a deterministic
arrival stream: per step, ``qps × step_period`` requests (fractional
remainders carried, so the OFFERED rate is exact over the run) land on
seeded claims/texts, then one ``tier.step()`` serves at most
``max_requests_per_step``.  The tier's service capacity is therefore
``max_requests_per_step / step_period`` QPS for cache misses, plus
whatever the dedup cache absorbs — the saturation knee the sweep is
built to show.

Per level the artifact (``BENCH_SERVING.json``) records p50/p99
request latency, goodput (completed requests per virtual second),
shed rate (total and per reason), cache hit rate, micro-batch
occupancy, and — when the real packed model runs (``--vectorizer
tiny``) — the ``packing_fill_ratio`` gauges from the cross-claim
packed forward.  The acceptance shape (ISSUE 7): shed ≈ 0 below the
knee; above it, p99 stays bounded (the queue bound + admission
control cap the tail) while shed goes nonzero — overload degrades into
rejected traffic, not into an unbounded latency tail.

Usage::

    python bench_serving.py [--seed 0] [--qps 40,80,...] [--out BENCH_SERVING.json]
    python bench_serving.py --vectorizer tiny   # real packed forward + fill ratios
"""

from __future__ import annotations

import os

# CPU by construction: saturation shape (queueing + admission), not
# device throughput, is what this bench certifies.  TPU numbers come
# from the hw campaign path.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import argparse  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402
import time  # noqa: E402
from typing import Any, Dict, List, Optional  # noqa: E402

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

#: Offered-QPS sweep (requests/virtual-second).  Default capacity is
#: max_requests=16 per 0.1 s step = 160 QPS of cache misses; the hot
#: pool pushes the effective knee a bit above that.  The sweep brackets
#: it from ~1/4× to 2×.
DEFAULT_QPS = (40, 80, 120, 160, 200, 240, 320)
#: Shared between :func:`run_level` and the p99 acceptance bound below —
#: the bound is derived from these, so tuning a knob cannot silently
#: detach it from the load it describes.
STEP_PERIOD_S = 0.1
MAX_REQUESTS_PER_STEP = 16
QUEUE_CAPACITY = 48


def make_tiny_vectorizer():
    """The real packed path at toy scale: TINY_TEST encoder + hash
    tokenizer.  ``MicroBatcher.vectorize`` routes through
    ``call_packed``, so the ``packing_fill_ratio{kind=}`` gauges
    measure genuine cross-claim segment occupancy."""
    from svoc_tpu.models.configs import TINY_TEST
    from svoc_tpu.models.sentiment import SentimentPipeline

    return SentimentPipeline(
        cfg=TINY_TEST, seq_len=32, batch_size=4, tokenizer_name=None
    )


def run_level(
    qps: float,
    *,
    seed: int = 0,
    n_claims: int = 4,
    n_oracles: int = 7,
    dimension: int = 6,
    step_period_s: float = STEP_PERIOD_S,
    steps: int = 40,
    warmup_steps: int = 5,
    max_requests_per_step: int = MAX_REQUESTS_PER_STEP,
    queue_capacity: int = QUEUE_CAPACITY,
    hot_pool: int = 12,
    hot_fraction: float = 0.3,
    vectorizer=None,
    cost_plane: Optional[str] = None,
) -> Dict[str, Any]:
    """One offered-QPS level: a fresh seeded tier under ``steps`` of
    open-loop arrivals; returns the level's metrics record.

    ``cost_plane`` pins the cost-attribution plane ``"on"`` / ``"off"``
    for this level (None inherits the tier default); ``bench_obs.py``
    A/Bs the two arms.  Latency percentiles are VIRTUAL time (identical
    across arms by fingerprint invariance), so the record also carries
    ``host_step_ms`` — real ``perf_counter`` per ``tier.step()`` over
    the measured window — which is where plane overhead would show."""
    from svoc_tpu.fabric.registry import ClaimSpec
    from svoc_tpu.fabric.scenario import _claim_names, deterministic_vectorizer
    from svoc_tpu.fabric.session import MultiSession
    from svoc_tpu.serving.frontend import AdmissionConfig
    from svoc_tpu.serving.scenario import (
        VirtualClock,
        draw_arrival,
        shed_by_reason,
    )
    from svoc_tpu.serving.tier import ServingTier
    from svoc_tpu.sim.generators import claim_seed
    from svoc_tpu.utils.events import EventJournal
    from svoc_tpu.utils.metrics import MetricsRegistry
    from svoc_tpu.utils.metrics import registry as global_registry
    from svoc_tpu.utils.slo import REQUEST_LATENCY_HISTOGRAM, serving_slos

    journal = EventJournal()
    metrics = MetricsRegistry()
    clock = VirtualClock()
    names = _claim_names(n_claims)
    vec = vectorizer if vectorizer is not None else deterministic_vectorizer

    multi = MultiSession(
        base_seed=seed,
        vectorizer=deterministic_vectorizer,
        journal=journal,
        metrics=metrics,
        lineage_scope="bsv",
        sanitized_dispatch=True,
        clock=clock,
    )
    for name in names:
        multi.add_claim(
            ClaimSpec(claim_id=name, n_oracles=n_oracles, dimension=dimension)
        )
    plane = None
    if cost_plane is not None:
        from svoc_tpu.obsplane.plane import CostPlane

        plane = CostPlane(
            enabled=(cost_plane == "on"), clock=clock, metrics=metrics
        )
    tier = ServingTier(
        multi,
        vectorizer=vec,
        admission=AdmissionConfig(
            queue_capacity=queue_capacity, burn_threshold=4.0, seed=seed
        ),
        max_requests_per_step=max_requests_per_step,
        clock=clock,
        cost_plane=plane,
        slos=serving_slos(
            metrics,
            latency_target_s=2.5 * step_period_s,
            fast_window_s=10 * step_period_s,
            slow_window_s=50 * step_period_s,
        ),
    )

    rng = np.random.default_rng(claim_seed(seed, f"bench_qps_{qps:g}"))
    pool = [f"hot take {i} shared across markets" for i in range(hot_pool)]
    carry = 0.0  # fractional-arrival accumulator: offered rate is exact
    step_detail: List[Dict[str, Any]] = []
    host_step_s: List[float] = []
    measured_submitted = 0
    shed_at_warmup = 0.0
    completed_at_warmup = 0.0
    for step_no in range(steps):
        clock.advance(step_period_s)
        carry += qps * step_period_s
        arrivals = int(carry)
        carry -= arrivals
        for i in range(arrivals):
            claim, text = draw_arrival(
                rng,
                names,
                pool,
                hot_fraction,
                lambda c: f"unique {c} q{qps:g} s{step_no} #{i}",
            )
            tier.submit(claim, text)
        t_host = time.perf_counter()
        report = tier.step()
        if step_no >= warmup_steps:
            host_step_s.append(time.perf_counter() - t_host)
        if step_no == warmup_steps - 1:
            shed_at_warmup = metrics.family_total("serving_shed")
            completed_at_warmup = metrics.family_total("serving_completed")
        if step_no >= warmup_steps:
            measured_submitted += arrivals
        # The pack path exports fill ratios to the PROCESS registry
        # (like its stage spans) — gauges are point-in-time values, not
        # part of any replay fingerprint, so reading them across the
        # fresh-per-level boundary is safe.
        fill = {
            kind: global_registry.gauge(
                "packing_fill_ratio", labels={"kind": kind}
            ).get()
            for kind in ("segments", "tokens")
        }
        step_detail.append(
            {
                "step": step_no,
                "arrivals": arrivals,
                "batched": report["requests"],
                "claims": report["claims"],
                "queue_depth": sum(tier.frontend.depths().values()),
                "shed_total": metrics.family_total("serving_shed"),
                "burn_rate": round(tier.frontend.controller.burn_rate(), 3),
                **(
                    {"packing_fill": fill}
                    if any(fill.values())
                    else {}
                ),
            }
        )

    latency = metrics.histogram(REQUEST_LATENCY_HISTOGRAM).snapshot()
    measured_span_s = (steps - warmup_steps) * step_period_s
    shed = metrics.family_total("serving_shed") - shed_at_warmup
    completed = metrics.family_total("serving_completed") - completed_at_warmup
    reason_totals = shed_by_reason(metrics)
    fill_final = {
        kind: global_registry.gauge(
            "packing_fill_ratio", labels={"kind": kind}
        ).get()
        for kind in ("segments", "tokens")
    }
    return {
        "offered_qps": qps,
        "steps": steps,
        "warmup_steps": warmup_steps,
        "measured_submitted": measured_submitted,
        "completed": completed,
        "shed": shed,
        "shed_rate": round(shed / max(measured_submitted, 1), 6),
        "goodput_qps": round(completed / measured_span_s, 3),
        "p50_ms": round(latency.get("p50", 0.0) * 1e3, 3),
        "p99_ms": round(latency.get("p99", 0.0) * 1e3, 3),
        "latency_count": latency.get("count", 0),
        "cache": tier.cache.stats(),
        "shed_by_reason": dict(sorted(reason_totals.items())),
        "journal_fingerprint": journal.fingerprint(),
        "host_step_ms": {
            "p50": round(float(np.percentile(host_step_s, 50)) * 1e3, 4),
            "p99": round(float(np.percentile(host_step_s, 99)) * 1e3, 4),
            "total_s": round(float(np.sum(host_step_s)), 4),
            "samples_s": host_step_s,
        },
        **(
            {"cost_plane": cost_plane} if cost_plane is not None else {}
        ),
        **(
            {"packing_fill_ratio": fill_final}
            if any(fill_final.values())
            else {}
        ),
        "step_detail": step_detail,
    }


def find_knee(sweep: List[Dict[str, Any]], shed_eps: float = 0.01) -> float:
    """The saturation knee: the highest offered QPS whose shed rate is
    ≤ ``shed_eps`` (0 when every level sheds)."""
    below = [r["offered_qps"] for r in sweep if r["shed_rate"] <= shed_eps]
    return max(below) if below else 0.0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--qps",
        default=",".join(str(q) for q in DEFAULT_QPS),
        help="comma-separated offered-QPS sweep",
    )
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--claims", type=int, default=4)
    p.add_argument(
        "--vectorizer",
        choices=("crc", "tiny"),
        default="crc",
        help=(
            "crc: the fabric scenario's deterministic text hash (fast; "
            "queueing shape only); tiny: the real packed TINY_TEST "
            "forward (adds packing_fill_ratio occupancy)"
        ),
    )
    p.add_argument("--out", default="BENCH_SERVING.json")
    args = p.parse_args(argv)

    # Ascending order is an invariant the endpoint acceptance checks
    # (lightest clean / heaviest sheds) rely on — sort, don't assume.
    qps_levels = sorted(float(tok) for tok in args.qps.split(",") if tok)
    vectorizer = make_tiny_vectorizer() if args.vectorizer == "tiny" else None

    sweep = []
    for qps in qps_levels:
        record = run_level(
            qps,
            seed=args.seed,
            n_claims=args.claims,
            steps=args.steps,
            vectorizer=vectorizer,
        )
        sweep.append(record)
        print(
            f"  qps {qps:7.1f}: goodput {record['goodput_qps']:7.1f}, "
            f"shed {record['shed_rate']:6.1%}, "
            f"p50 {record['p50_ms']:7.1f} ms, "
            f"p99 {record['p99_ms']:7.1f} ms, "
            f"cache hit {record['cache']['hit_rate']:.1%}"
        )

    knee = find_knee(sweep)
    above = [r for r in sweep if r["offered_qps"] > knee]
    below = [r for r in sweep if r["offered_qps"] <= knee]
    knee_goodput = max((r["goodput_qps"] for r in below), default=0.0)
    # The acceptance shape: a knee exists inside the sweep, shed ≈ 0
    # below it, and above it shedding is nonzero while p99 stays
    # bounded (admission + the queue bound cap the tail — use the
    # queue-capacity wait as the bound).
    p99_bound_ms = None
    if above:
        # One queue holds ≤ capacity requests served ≥ (max_requests /
        # n_claims) per step under fair round-robin; double it for the
        # bucketized histogram edges.
        p99_bound_ms = 2e3 * STEP_PERIOD_S * (
            QUEUE_CAPACITY / max(MAX_REQUESTS_PER_STEP / args.claims, 1)
        )
    checks = {
        "knee_inside_sweep": bool(
            knee and any(r["offered_qps"] > knee for r in sweep)
        ),
        # Anchored to the sweep ENDPOINTS, not to find_knee's own shed
        # predicate (below/above-the-knee shed checks would be
        # tautologies of the knee definition): the lightest offered
        # load must be clean and the heaviest must shed materially.
        "lightest_level_clean": sweep[0]["shed_rate"] <= 0.01,
        "heaviest_level_sheds": sweep[-1]["shed_rate"] >= 0.10,
        "p99_bounded_above_knee": (
            all(r["p99_ms"] <= p99_bound_ms for r in above)
            if p99_bound_ms is not None
            else False
        ),
        # Saturation is measured against the CAPACITY goodput (the best
        # below-knee level, knee inclusive): above the knee, goodput
        # must neither keep growing (no saturation → the knee was
        # noise) nor collapse (shedding should hold goodput up, not
        # drop the floor out).
        "goodput_saturates": (
            bool(above)
            and knee_goodput > 0
            and max(r["goodput_qps"] for r in above) <= 1.25 * knee_goodput
            and min(r["goodput_qps"] for r in above) >= 0.25 * knee_goodput
        ),
    }
    ok = all(checks.values())
    from bench import device_topology

    artifact = {
        "seed": args.seed,
        "vectorizer": args.vectorizer,
        "claims": args.claims,
        "device_topology": device_topology(),
        "steps_per_level": args.steps,
        "knee_qps": knee,
        "p99_bound_ms": p99_bound_ms,
        "checks": checks,
        "ok": ok,
        "sweep": sweep,
    }
    with open(args.out + ".tmp", "w") as f:
        json.dump(artifact, f, indent=1)
    os.replace(args.out + ".tmp", args.out)
    for name, passed in checks.items():
        print(f"  {'PASS' if passed else 'FAIL'}  {name}")
    print(
        f"bench-serving {'OK' if ok else 'FAILED'}: knee ~{knee:g} QPS "
        f"over {len(sweep)} levels -> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
