"""Kill/restart chaos gate: crash-consistent durability as CI
(``make crash-smoke``; docs/RESILIENCE.md §durability + §fault-surface).

Each leg of the matrix is a chain of subprocess phases in one work
directory, SIGKILLed at a NAMED fault point
(:mod:`svoc_tpu.durability.faultspace`; the scenario maps each leg onto
a registry event — ``svoc_tpu/durability/scenario.py``):

- ``mid_wal_append`` — ``torn`` @ ``wal.intent.pre_fsync`` (a
  commit-intent record torn in half mid-fsync);
- ``inter_tx`` — ``kill`` @ ``chainlog.tx.post_fsync`` (between tx *i*
  landing on the chain log and its WAL ``landed`` record);
- ``pre_snapshot`` — ``kill`` @ ``serving.step.post`` (after a serving
  step's commits, before its cadence snapshot);
- ``batch_mid_fleet`` — ``kill`` @ ``chain.batch.mid_fleet`` with
  ``commit_mode="batched"``: the one-RPC batched commit killed while
  logging its txs; the restart reconciler must classify the durable
  prefix via its ``landed_batch``/chain-digest columns and resend only
  the suffix (closing the PR 13 unit-test-only gap end-to-end);
- ``recovery_storm`` — an ``inter_tx`` crash whose RECOVERY child is
  itself killed at ``recovery.post_restore`` (ring restored, counters
  not re-seeded, WAL not reconciled); the third child's recovery must
  be idempotent.

After every chain's final (clean) child: **zero duplicate txs** in any
chain log, **zero unknown and zero unaccounted WAL slots**, **zero
unaccounted admitted requests**, **zero open WAL cycles**, and each
leg's named fault point present in the durable fired log.  The FULL
matrix runs twice; the recovered per-claim journal fingerprints must be
byte-identical across the two matrix runs — the recovery path itself is
part of the replay witness.

Usage::

    python tools/crash_smoke.py [--seed 0] [--out CRASH_SMOKE.json]
    python tools/crash_smoke.py --child <workdir> [--crash-point P] \\
        [--commit-mode M]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform —
# tools/soak.py measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import json  # noqa: E402
import signal  # noqa: E402
import subprocess  # noqa: E402
import sys  # noqa: E402
import tempfile  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from svoc_tpu.durability.faultspace import read_fired_log  # noqa: E402
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

TOTAL_STEPS = 8

#: Leg → (phase chain, commit mode).  Every phase but the last must die
#: by SIGKILL; the last recovers, drains, and writes result.json.
LEGS = {
    "mid_wal_append": (("mid_wal_append", None), "per_tx"),
    "inter_tx": (("inter_tx", None), "per_tx"),
    "pre_snapshot": (("pre_snapshot", None), "per_tx"),
    "batch_mid_fleet": (("batch_mid_fleet", None), "batched"),
    # The restart storm: crash, then kill the recovery itself, then a
    # third child whose recovery must be idempotent.
    "recovery_storm": (("inter_tx", "recovery_storm", None), "per_tx"),
}

#: The named point each leg must prove fired (the crash half of the
#: declared-coverage contract; ``make chaos-fuzz-smoke`` owns the rest).
LEG_POINT = {
    "mid_wal_append": "wal.intent.pre_fsync",
    "inter_tx": "chainlog.tx.post_fsync",
    "pre_snapshot": "serving.step.post",
    "batch_mid_fleet": "chain.batch.mid_fleet",
    "recovery_storm": "recovery.post_restore",
}


def child_main(args) -> int:
    from svoc_tpu.durability.scenario import run_durable_scenario

    result = run_durable_scenario(
        args.child,
        seed=args.seed,
        total_steps=TOTAL_STEPS,
        crash_point=args.crash_point,
        commit_mode=args.commit_mode,
    )
    # Only the non-crashing (recovery / clean) phase reaches here.
    with open(os.path.join(args.child, "result.json"), "w") as f:
        json.dump(result, f, indent=1)
    return 0


def spawn_child(
    workdir: str, seed: int, crash_point=None, commit_mode=None
) -> subprocess.Popen:
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--child", workdir, "--seed", str(seed),
    ]
    if crash_point is not None:
        cmd += ["--crash-point", crash_point]
    if commit_mode is not None:
        cmd += ["--commit-mode", commit_mode]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env,
    )


def run_matrix(seed: int, legs, base_dir: str) -> dict:
    """One full kill/restart matrix.  The legs use disjoint work
    directories, so each phase wave runs the legs in parallel — each
    child still pays the full cold-process jax import (that isolation
    IS the experiment), but the waves overlap it."""
    out = {
        leg: {"crash_point": leg, "killed": [], "result": None,
              "fired": None, "notes": []}
        for leg in legs
    }
    for leg in legs:
        os.makedirs(os.path.join(base_dir, leg), exist_ok=True)
    max_phases = max(len(LEGS[leg][0]) for leg in legs)
    for phase in range(max_phases):
        procs = {}
        for leg in legs:
            chain, commit_mode = LEGS[leg]
            if phase >= len(chain):
                continue
            procs[leg] = (
                spawn_child(
                    os.path.join(base_dir, leg), seed,
                    crash_point=chain[phase], commit_mode=commit_mode,
                ),
                chain[phase] is not None,  # expect SIGKILL?
            )
        for leg, (proc, expect_kill) in procs.items():
            _stdout, stderr = proc.communicate()
            killed = proc.returncode == -signal.SIGKILL
            out[leg]["killed"].append(killed)
            if expect_kill and not killed:
                out[leg]["notes"].append(
                    f"phase {phase} exited {proc.returncode}, expected "
                    f"SIGKILL; stderr tail: {stderr[-500:]}"
                )
            elif not expect_kill:
                if proc.returncode != 0:
                    out[leg]["notes"].append(
                        f"recovery phase exited {proc.returncode}; "
                        f"stderr tail: {stderr[-500:]}"
                    )
                else:
                    workdir = os.path.join(base_dir, leg)
                    with open(os.path.join(workdir, "result.json")) as f:
                        out[leg]["result"] = json.load(f)
                    out[leg]["fired"] = read_fired_log(
                        os.path.join(workdir, "fired.jsonl")
                    )
    return out


def check_matrix(matrix: dict) -> dict:
    checks = {}
    for leg, entry in matrix.items():
        chain, _mode = LEGS[leg]
        r = entry["result"]
        kills_ok = (
            len(entry["killed"]) == len(chain)
            and all(entry["killed"][:-1])
            and not entry["killed"][-1]
        )
        fired = (entry["fired"] or {}).get("fired", [])
        rec = (r or {}).get("recovery") or {}
        reconcile = rec.get("reconcile") or {}
        checks[leg] = {
            "killed_by_sigkill": kills_ok,
            "recovered": bool(r and r["recovered"]),
            "zero_duplicate_txs": bool(r and r["duplicate_txs"] == 0),
            "zero_open_wal_cycles": bool(r and not r["wal_open_cycles"]),
            "zero_unknown_slots": reconcile.get("unknown", 0) == 0,
            "zero_unaccounted_slots": reconcile.get("unaccounted", 0) == 0,
            "zero_unaccounted_requests": bool(
                r and r["requests"]["unaccounted"] == 0
            ),
            "ran_to_completion": bool(r and r["steps"] == TOTAL_STEPS),
            "named_point_fired": LEG_POINT[leg] in fired,
            "notes": entry["notes"],
        }
        if leg == "batch_mid_fleet":
            # The PR 13 gap, closed: the mid-batch kill must classify
            # through the reconciler's landed_batch/chain-digest
            # columns — a durable prefix held (landed), a suffix resent.
            counts = _reconcile_counts(reconcile)
            checks[leg]["batch_prefix_landed"] = (
                counts.get("landed_chain", 0)
                + counts.get("landed_batch", 0)
                + counts.get("landed_durable", 0)
            ) >= 1
            checks[leg]["batch_suffix_resent"] = (
                reconcile.get("resent", 0) >= 1
            )
        checks[leg]["ok"] = all(
            v for k, v in checks[leg].items() if k != "notes"
        )
    return checks


def _reconcile_counts(reconcile: dict) -> dict:
    totals: dict = {}
    for cyc in reconcile.get("cycles", []):
        for k, v in (cyc.get("counts") or {}).items():
            totals[k] = totals.get(k, 0) + v
    return totals


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="CRASH_SMOKE.json")
    p.add_argument("--child", default=None, help="(internal) scenario workdir")
    p.add_argument(
        "--crash-point", default=None,
        choices=sorted({pt for chain, _ in LEGS.values()
                        for pt in chain if pt}),
    )
    p.add_argument("--commit-mode", default=None,
                   choices=["per_tx", "batched"])
    args = p.parse_args(argv)
    if args.child is not None:
        return child_main(args)

    legs = list(LEGS)
    base = tempfile.mkdtemp(prefix="crash-smoke-")
    first = run_matrix(args.seed, legs, os.path.join(base, "run1"))
    second = run_matrix(args.seed, legs, os.path.join(base, "run2"))
    checks = check_matrix(first)

    fingerprints = {}
    for leg in legs:
        r1 = first[leg]["result"] or {}
        r2 = second[leg]["result"] or {}
        c1 = {c: v["fingerprint"] for c, v in (r1.get("claims") or {}).items()}
        c2 = {c: v["fingerprint"] for c, v in (r2.get("claims") or {}).items()}
        fingerprints[leg] = {
            "identical": bool(c1) and c1 == c2,
            "claims": c1,
        }
    all_checks = {
        f"{leg}.{name}": value
        for leg, ch in checks.items()
        for name, value in ch.items()
        if name not in ("ok", "notes")
    }
    all_checks["recovered_fingerprints_identical_across_matrix_runs"] = all(
        f["identical"] for f in fingerprints.values()
    )
    ok = all(all_checks.values())
    artifact = {
        "seed": args.seed,
        "total_steps": TOTAL_STEPS,
        "crash_points": legs,
        "checks": checks,
        "fingerprints": fingerprints,
        "ok": ok,
        "matrix": {
            leg: {
                "killed": first[leg]["killed"],
                "commit_mode": LEGS[leg][1],
                "fired": first[leg]["fired"],
                "notes": first[leg]["notes"],
                "result": first[leg]["result"],
            }
            for leg in legs
        },
    }
    atomic_write_json(args.out, artifact)
    for name, passed in sorted(all_checks.items()):
        print(f"  {'PASS' if passed else 'FAIL'}  {name}")
    print(
        f"crash-smoke {'OK' if ok else 'FAILED'}: "
        f"{len(legs)} kill legs (incl. batched mid-fleet + restart "
        f"storm) x 2 matrix runs, 0 duplicate txs asserted over the "
        f"chain logs -> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
