"""Kill/restart chaos gate: crash-consistent durability as CI
(``make crash-smoke``; docs/RESILIENCE.md §durability).

For each seeded fault point — ``mid_wal_append`` (a commit-intent
record torn in half mid-fsync), ``inter_tx`` (SIGKILL between tx *i*
landing on the chain log and its WAL ``landed`` record), and
``pre_snapshot`` (SIGKILL after a serving step's commits, before its
cadence snapshot) — the harness:

1. runs the seeded serving scenario
   (:func:`svoc_tpu.durability.scenario.run_durable_scenario`) in a
   SUBPROCESS that SIGKILLs itself at the fault point (asserted: the
   child died by SIGKILL, not cleanly);
2. re-runs the same scenario in the same work directory: the child
   auto-detects the durable state and recovers (snapshot restore →
   fingerprint-checked journal ring → trace-tail replay → WAL
   reconcile → resume serving → graceful drain);
3. asserts over the recovered child's result:
   **zero duplicate txs** in any chain log, **zero unknown and zero
   unaccounted WAL slots** (the backend is reachable — every intent
   classifies landed or stranded-resent), **zero unaccounted admitted
   requests**, **zero open WAL cycles** after the drain.

The FULL matrix runs twice; the recovered per-claim journal
fingerprints must be byte-identical across the two matrix runs — the
recovery path itself is part of the replay witness.

Usage::

    python tools/crash_smoke.py [--seed 0] [--out CRASH_SMOKE.json]
    python tools/crash_smoke.py --child <workdir> [--crash-point P]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform —
# tools/soak.py measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import json  # noqa: E402
import signal  # noqa: E402
import subprocess  # noqa: E402
import sys  # noqa: E402
import tempfile  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

TOTAL_STEPS = 8


def child_main(args) -> int:
    from svoc_tpu.durability.scenario import run_durable_scenario

    result = run_durable_scenario(
        args.child,
        seed=args.seed,
        total_steps=TOTAL_STEPS,
        crash_point=args.crash_point,
    )
    # Only the non-crashing (recovery / clean) phase reaches here.
    with open(os.path.join(args.child, "result.json"), "w") as f:
        json.dump(result, f, indent=1)
    return 0


def spawn_child(workdir: str, seed: int, crash_point=None) -> subprocess.Popen:
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--child", workdir, "--seed", str(seed),
    ]
    if crash_point is not None:
        cmd += ["--crash-point", crash_point]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env,
    )


def run_matrix(seed: int, crash_points, base_dir: str) -> dict:
    """One full kill/restart matrix.  The fault points use disjoint
    work directories, so the crash children run as one parallel wave
    and the recovery children as a second — each child still pays the
    full cold-process jax import (that isolation IS the experiment),
    but the waves overlap it."""
    out = {
        point: {"crash_point": point, "killed": None, "result": None,
                "notes": []}
        for point in crash_points
    }
    for point in crash_points:
        os.makedirs(os.path.join(base_dir, point), exist_ok=True)
    crash_procs = {
        point: spawn_child(
            os.path.join(base_dir, point), seed, crash_point=point
        )
        for point in crash_points
    }
    for point, proc in crash_procs.items():
        _stdout, stderr = proc.communicate()
        out[point]["killed"] = proc.returncode == -signal.SIGKILL
        if not out[point]["killed"]:
            out[point]["notes"].append(
                f"child exited {proc.returncode}, expected SIGKILL; "
                f"stderr tail: {stderr[-500:]}"
            )
    recover_procs = {
        point: spawn_child(os.path.join(base_dir, point), seed)
        for point in crash_points
    }
    for point, proc in recover_procs.items():
        _stdout, stderr = proc.communicate()
        if proc.returncode != 0:
            out[point]["notes"].append(
                f"recovery child exited {proc.returncode}; "
                f"stderr tail: {stderr[-500:]}"
            )
        else:
            with open(os.path.join(base_dir, point, "result.json")) as f:
                out[point]["result"] = json.load(f)
    return out


def check_matrix(matrix: dict) -> dict:
    checks = {}
    for point, entry in matrix.items():
        r = entry["result"]
        ok = (
            entry["killed"]
            and r is not None
            and r["recovered"]
            and r["duplicate_txs"] == 0
            and all(c["duplicates"] == 0 for c in r["chain"].values())
            and not r["wal_open_cycles"]
            and r["requests"]["unaccounted"] == 0
            and r["steps"] == TOTAL_STEPS
        )
        rec = (r or {}).get("recovery") or {}
        reconcile = rec.get("reconcile") or {}
        checks[point] = {
            "killed_by_sigkill": bool(entry["killed"]),
            "recovered": bool(r and r["recovered"]),
            "zero_duplicate_txs": bool(r and r["duplicate_txs"] == 0),
            "zero_open_wal_cycles": bool(r and not r["wal_open_cycles"]),
            "zero_unknown_slots": reconcile.get("unknown", 0) == 0,
            "zero_unaccounted_slots": reconcile.get("unaccounted", 0) == 0,
            "zero_unaccounted_requests": bool(
                r and r["requests"]["unaccounted"] == 0
            ),
            "ran_to_completion": bool(r and r["steps"] == TOTAL_STEPS),
            "ok": ok,
            "notes": entry["notes"],
        }
    return checks


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="CRASH_SMOKE.json")
    p.add_argument("--child", default=None, help="(internal) scenario workdir")
    p.add_argument(
        "--crash-point", default=None,
        choices=["mid_wal_append", "inter_tx", "pre_snapshot"],
    )
    args = p.parse_args(argv)
    if args.child is not None:
        return child_main(args)

    from svoc_tpu.durability.scenario import CRASH_POINTS

    base = tempfile.mkdtemp(prefix="crash-smoke-")
    first = run_matrix(args.seed, CRASH_POINTS, os.path.join(base, "run1"))
    second = run_matrix(args.seed, CRASH_POINTS, os.path.join(base, "run2"))
    checks = check_matrix(first)

    fingerprints = {}
    for point in CRASH_POINTS:
        r1 = first[point]["result"] or {}
        r2 = second[point]["result"] or {}
        c1 = {c: v["fingerprint"] for c, v in (r1.get("claims") or {}).items()}
        c2 = {c: v["fingerprint"] for c, v in (r2.get("claims") or {}).items()}
        fingerprints[point] = {
            "identical": bool(c1) and c1 == c2,
            "claims": c1,
        }
    all_checks = {
        f"{point}.{name}": value
        for point, ch in checks.items()
        for name, value in ch.items()
        if name not in ("ok", "notes")
    }
    all_checks["recovered_fingerprints_identical_across_matrix_runs"] = all(
        f["identical"] for f in fingerprints.values()
    )
    ok = all(all_checks.values())
    artifact = {
        "seed": args.seed,
        "total_steps": TOTAL_STEPS,
        "crash_points": list(CRASH_POINTS),
        "checks": checks,
        "fingerprints": fingerprints,
        "ok": ok,
        "matrix": {
            point: {
                "killed": first[point]["killed"],
                "notes": first[point]["notes"],
                "result": first[point]["result"],
            }
            for point in CRASH_POINTS
        },
    }
    atomic_write_json(args.out, artifact)
    for name, passed in sorted(all_checks.items()):
        print(f"  {'PASS' if passed else 'FAIL'}  {name}")
    print(
        f"crash-smoke {'OK' if ok else 'FAILED'}: "
        f"{len(CRASH_POINTS)} kill points x 2 matrix runs, "
        f"0 duplicate txs asserted over the chain logs -> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
