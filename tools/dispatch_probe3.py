#!/usr/bin/env python
"""Probe 3: decompose execution vs fetch on the axon tunnel.

Dispatch N unique-input forwards back-to-back and fetch ONLY the last
result.  If the device serializes execution, the final fetch waits for
all N executions, so total/N approximates true per-step execution with
the ~67 ms roundtrip amortized.  Compare N in {1, 8, 32} and a
fetch-every-8 variant, plus chained steps (output feeds consensus) to
mirror the flagship loop.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    result = {"backend": jax.default_backend()}

    from svoc_tpu.models.configs import ROBERTA_GO_EMOTIONS
    from svoc_tpu.models.sentiment import SentimentPipeline

    B, S = 256, 128
    pipe = SentimentPipeline(
        cfg=ROBERTA_GO_EMOTIONS, seq_len=S, batch_size=B, tokenizer_name=None
    )
    fwd = pipe.forward_fn()
    rng = np.random.default_rng(0)
    n_uniq = 16
    ids_pool = [
        jax.device_put(jnp.asarray(rng.integers(10, 5000, (B, S)), jnp.int32))
        for _ in range(n_uniq)
    ]
    mask = jax.device_put(jnp.ones((B, S), jnp.int32))
    _ = float(jnp.sum(fwd(pipe.params, ids_pool[0], mask)))  # warm

    j = [0]

    def run_n_fetch_last(n):
        out = None
        for _ in range(n):
            j[0] += 1
            out = fwd(pipe.params, ids_pool[j[0] % n_uniq], mask)
        return float(jnp.sum(out))

    for n in (1, 8, 32):
        run_n_fetch_last(n)  # warm the pattern
        t0 = time.perf_counter()
        run_n_fetch_last(n)
        dt = time.perf_counter() - t0
        result[f"dispatch{n}_fetch_last_s"] = round(dt, 3)
        result[f"dispatch{n}_per_step_ms"] = round(dt / n * 1e3, 2)

    flops = 256 * 128 * 12 * (2 * (4 * 768 * 768 + 2 * 768 * 3072) + 4 * 128 * 768)
    per_step_s = result["dispatch32_per_step_ms"] / 1e3
    result["fwd_matmul_tflop"] = round(flops / 1e12, 3)
    result["amortized_implied_tflops"] = round(flops / per_step_s / 1e12, 1)
    result["amortized_implied_mfu"] = round(
        result["amortized_implied_tflops"] / 197.0, 3
    )

    line = json.dumps(result)
    print(line, flush=True)
    with open("DISPATCH_PROBE3.json", "w") as fh:
        fh.write(line + "\n")


if __name__ == "__main__":
    main()
