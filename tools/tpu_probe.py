#!/usr/bin/env python
"""On-chip TPU probes: settle the kernel-compile questions empirically.

Each probe runs in its own SUBPROCESS under a hard timeout, so a hung
remote compiler (the known failure mode of the tunneled TPU backend)
is contained and reported as ``{"ok": false, "timeout": true}`` instead
of wedging the caller.  Results are printed as JSON lines and written to
``TPU_PROBE.json`` at the repo root.

Probes:

1. ``backend``        — backend init + device kind (the canary).
2. ``grid_copy``      — a trivial 2-D-grid ``pallas_call`` copy kernel:
                        decides whether "gridded pallas_call hangs the
                        axon compiler" (round-1 folklore) is real.
3. ``consensus1024``  — gridless fused consensus @1024: compile time +
                        latency vs the XLA kernel.
4. ``flash512``       — flash attention, B=8 T=512 H=12 D=64, compile +
                        latency vs the XLA dense path.
5. ``encoder512``     — full encoder forward at seq 512 with the dense
                        and the flash (cfg.attention) encoder.

Usage: ``python tools/tpu_probe.py [--only NAME] [--timeout S]``
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

PROBES: dict = {}


def probe(name):
    def wrap(src):
        PROBES[name] = src
        return src

    return wrap


# Each probe is python source executed in a fresh interpreter; it must
# print exactly one JSON object on its last stdout line.  The prelude
# honors SVOC_PROBE_PLATFORM (e.g. "cpu") via jax.config — the
# environment's sitecustomize pins the platform regardless of
# JAX_PLATFORMS, so an env var alone cannot redirect a probe.

PRELUDE = """
import os as _os
import jax as _jax
if _os.environ.get("SVOC_PROBE_PLATFORM"):
    _jax.config.update("jax_platforms", _os.environ["SVOC_PROBE_PLATFORM"])

# Honest timing (round 3): block_until_ready returns before execution on
# the tunneled backend, so all latencies are host-fetch amortized.
import time as _time
import numpy as _np

def _fetch(_x):
    import jax.numpy as _jnp
    _leaves = [l for l in _jax.tree_util.tree_leaves(_x) if hasattr(l, "dtype")]
    _tot = sum(_jnp.sum(_jnp.asarray(l, _jnp.float32)) for l in _leaves)
    return float(_np.asarray(_tot))

def lat(fn, reps=16):
    _fetch(fn())  # warm
    _t0 = _time.time()
    _h = None
    for _ in range(reps):
        _h = fn()
    _fetch(_h)
    return (_time.time() - _t0) / reps * 1e3
"""

PROBES["backend"] = """
import json, time, jax
t0 = time.time()
devs = jax.devices()
print(json.dumps({"platform": devs[0].platform, "device_kind": devs[0].device_kind,
                  "n_devices": len(devs), "init_s": round(time.time() - t0, 1)}))
"""

PROBES["grid_copy"] = """
import json, time
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]

x = jnp.arange(4 * 256 * 128, dtype=jnp.float32).reshape(4, 256, 128)
t0 = time.time()
out = pl.pallas_call(
    copy_kernel,
    grid=(4, 2),
    in_specs=[pl.BlockSpec((1, 128, 128), lambda i, j: (i, j, 0),
                           memory_space=pltpu.VMEM)],
    out_specs=pl.BlockSpec((1, 128, 128), lambda i, j: (i, j, 0),
                           memory_space=pltpu.VMEM),
    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
)(x)
out.block_until_ready()
ok = bool((out == x).all())
print(json.dumps({"grid_compiles": True, "correct": ok,
                  "compile_s": round(time.time() - t0, 1)}))
"""

PROBES["consensus1024"] = """
import json, os, time
import jax, jax.numpy as jnp
from svoc_tpu.consensus.kernel import ConsensusConfig, consensus_step
from svoc_tpu.ops.pallas_consensus import fused_consensus

# Size-bisect support: the 2026-07-30 on-chip run saw this probe hang
# at N=1024 (Mosaic compile); SVOC_PROBE_N_ORACLES lets main() walk
# sizes upward and localize where the hang starts.
n, dim = int(os.environ.get("SVOC_PROBE_N_ORACLES", "1024")), 6
cfg = ConsensusConfig(n_failing=n // 4, constrained=True)
values = jax.random.uniform(jax.random.PRNGKey(0), (n, dim), minval=0.01, maxval=0.99)

xla_step = jax.jit(lambda v: consensus_step(v, cfg))
t0 = time.time(); jax.block_until_ready(xla_step(values)); xla_compile = time.time() - t0

t0 = time.time(); jax.block_until_ready(fused_consensus(values, cfg))
pallas_compile = time.time() - t0

xla_ms = lat(lambda: xla_step(values))
pallas_ms = lat(lambda: fused_consensus(values, cfg))
import numpy as np
a = fused_consensus(values, cfg); b = xla_step(values)
match = bool(np.allclose(np.asarray(a.essence), np.asarray(b.essence), atol=1e-5))
print(json.dumps({"pallas_compile_s": round(pallas_compile, 1),
                  "xla_compile_s": round(xla_compile, 1),
                  "pallas_ms": round(pallas_ms, 3), "xla_ms": round(xla_ms, 3),
                  "speedup": round(xla_ms / pallas_ms, 2), "essence_match": match}))
"""

PROBES["flash512"] = """
import json, time
import jax, jax.numpy as jnp
import numpy as np
from svoc_tpu.ops.pallas_attention import flash_attention
from svoc_tpu.parallel.ring_attention import dense_attention_reference

b, t, h, d = 8, 512, 12, 64
kq = jax.random.PRNGKey(0)
q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
mask = jnp.ones((b, t), jnp.int32)

t0 = time.time()
out = flash_attention(q, q, q, mask)
jax.block_until_ready(out)
compile_s = time.time() - t0
ref = dense_attention_reference(q, q, q, mask)
# Dtype-aware verdict (round-4 postmortem: a naive atol 2e-3 sat BELOW
# one bf16 ulp of the output scale, so this probe cried
# "match_dense: false" over pure matmul rounding — the TPU MXU rounds
# inputs to bf16 at default precision even for f32 arrays.  Full
# adjudication: tools/flash_probe.py --parity-only).
diff = float(np.max(np.abs(np.asarray(out) - np.asarray(ref))))
scale = float(np.max(np.abs(np.asarray(ref))))
bound = 4.0 * 2.0 ** -8 * scale
match = diff <= bound

dense_jit = jax.jit(dense_attention_reference)
flash_ms = lat(lambda: flash_attention(q, q, q, mask))
dense_ms = lat(lambda: dense_jit(q, q, q, mask))
print(json.dumps({"flash_compiles": True, "compile_s": round(compile_s, 1),
                  "match_dense": match, "max_abs_diff": diff,
                  "dtype_bound": round(bound, 6),
                  "flash_ms": round(flash_ms, 3),
                  "dense_ms": round(dense_ms, 3),
                  "speedup": round(dense_ms / flash_ms, 2)}))
"""

PROBES["encoder512"] = """
import json, time, os, dataclasses
import jax, jax.numpy as jnp
from svoc_tpu.models.configs import ROBERTA_GO_EMOTIONS
from svoc_tpu.models.encoder import SentimentEncoder, init_params

flash = os.environ.get("SVOC_PROBE_ATTENTION") == "flash"
cfg = dataclasses.replace(
    ROBERTA_GO_EMOTIONS, attention="flash" if flash else "dense"
)
model = SentimentEncoder(cfg)
params = init_params(model, seed=0)
b, t = 32, 512
ids = jnp.ones((b, t), jnp.int32)
mask = jnp.ones((b, t), jnp.int32)

fwd = jax.jit(lambda p, i, m: model.apply(p, i, m))
t0 = time.time(); jax.block_until_ready(fwd(params, ids, mask))
compile_s = time.time() - t0

ms = lat(lambda: fwd(params, ids, mask))
print(json.dumps({"flash_enabled": flash, "compile_s": round(compile_s, 1),
                  "forward_ms": round(ms, 3),
                  "comments_per_sec": round(b / (ms / 1e3), 1)}))
"""


def run_probe(name: str, timeout_s: float, extra_env: dict | None = None) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{REPO}:{env.get('PYTHONPATH', '')}"
    env.update(extra_env or {})
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", PRELUDE + PROBES[name]],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return {
            "probe": name,
            "ok": False,
            "timeout": True,
            "elapsed_s": round(time.time() - t0, 1),
        }
    result: dict = {
        "probe": name,
        "ok": proc.returncode == 0,
        "elapsed_s": round(time.time() - t0, 1),
    }
    if proc.returncode == 0:
        try:
            result.update(json.loads(proc.stdout.strip().splitlines()[-1]))
        except (ValueError, IndexError):
            result["ok"] = False
            result["stdout_tail"] = proc.stdout[-300:]
    else:
        result["stderr_tail"] = (proc.stderr or "").strip().splitlines()[-3:]
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--only", choices=sorted(PROBES), default=None)
    parser.add_argument("--timeout", type=float, default=420.0)
    args = parser.parse_args(argv)

    names = [args.only] if args.only else list(PROBES)
    results = []
    out_path = os.path.join(REPO, "TPU_PROBE.json")

    def record(r):
        """Print + persist after EVERY probe: an outer kill (campaign
        item timeout, operator) must not lose completed probes."""
        print(json.dumps(r), flush=True)
        results.append(r)
        atomic_write_json(out_path, results)

    for name in names:
        extra = {}
        if name == "consensus1024":
            # Size bisect, ascending; stop at the first hang — larger
            # sizes would only burn more of the alive window.
            hung = False
            for n_oracles in (128, 256, 512):
                r1 = run_probe(
                    name, args.timeout, {"SVOC_PROBE_N_ORACLES": str(n_oracles)}
                )
                r1["probe"] = f"consensus{n_oracles}"
                record(r1)
                if r1.get("timeout"):
                    hung = True
                    break
            if hung:
                continue
            extra = {"SVOC_PROBE_N_ORACLES": "1024"}
        if name == "encoder512":
            # run twice: dense, then the flash-attention encoder config
            r1 = run_probe(name, args.timeout, {"SVOC_PROBE_ATTENTION": "dense"})
            r1["probe"] = "encoder512_dense"
            record(r1)
            extra = {"SVOC_PROBE_ATTENTION": "flash"}
        r = run_probe(name, args.timeout, extra)
        if name == "encoder512":
            r["probe"] = "encoder512_flash"
        record(r)
        if name == "backend" and not r["ok"]:
            print(json.dumps({"abort": "backend unreachable"}))
            break

    return 0 if all(r.get("ok") for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
