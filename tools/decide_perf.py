#!/usr/bin/env python
"""Derive PERF_DECISIONS.json from measured hardware results.

Reads ``HW_CAMPAIGN.json`` (and/or ``HW_QUEUE_RESULTS.json``) and
applies the FIXED decision rules below, so the routing the flagship
bench and the serving paths follow is a reproducible function of
committed measurements — not an editorial choice:

- ``flagship_variant`` — the throughput argmax among the LOSSLESS
  end-to-end variants measured on the TPU backend: config 0 (dense),
  config 8 (packed), config 12 (packed x flash).  int8 configs are
  excluded: they trade accuracy and stay opt-in.
- ``consensus_impl`` — "pallas" iff config 6 measured the fused kernel
  on the TPU backend with ``pallas_vs_xla_speedup > 1``, no hang, and
  XLA-matching essence; "xla" otherwise (including by walkover when
  the Mosaic compile hung — the VERDICT r2 decision rule).

A decision is only derived from results whose ``detail.backend`` is
``"tpu"`` with no fallback/small-mode label; with no qualifying
measurements the tool writes nothing (exit 3) — the defaults in
``bench.py`` stay in force.

Usage::

    python tools/decide_perf.py [--dry-run]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "PERF_DECISIONS.json")

sys.path.insert(0, REPO)
from bench import LOSSLESS_VARIANT_CONFIGS  # noqa: E402

# {item_name: variant} derived from bench.py's single mapping so the
# decision rules and the replay routing can never drift.
LOSSLESS_VARIANTS = {
    f"bench_config{cfg}": variant
    for variant, cfg in LOSSLESS_VARIANT_CONFIGS.items()
}


def is_tpu_result(result: dict) -> bool:
    detail = result.get("detail", {})
    return (
        detail.get("backend") == "tpu"
        and not detail.get("backend_fallback")
        and not detail.get("small_mode")
    )


def iter_result_entries(paths):
    """Yield ``(path, item_name, res_dict)`` for every result entry in
    the given queue/campaign artifacts, tolerating both journal shapes
    (items with a ``results`` list vs flat one-shot items) and skipping
    malformed entries instead of crashing — the single journal-walking
    loop shared by every evidence scan in this module."""
    for path in paths:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        items = data.get("items") if isinstance(data, dict) else data
        for item in items or []:
            if not isinstance(item, dict):
                continue
            name = item.get("name", item.get("probe", ""))
            results = item.get("results")
            for res in results if isinstance(results, list) else [item]:
                if isinstance(res, dict):
                    yield path, name, res


def latest_tpu_results(paths) -> dict:
    """``{item_name: result}`` — last qualifying TPU result per item
    across the given artifacts (later files win)."""
    found = {}
    for _path, name, res in iter_result_entries(paths):
        result = res.get("result")
        # Only CLEAN attempts qualify: a bench that printed its result
        # line but exited nonzero (teardown crash, MFU hard-fail) was
        # rejected by the queue itself, and a campaign_replay line is
        # recycled data, not a capture — neither may drive the
        # committed routing.
        if (
            res.get("rc") == 0
            and isinstance(result, dict)
            and is_tpu_result(result)
            and not result.get("detail", {}).get("replayed_from")
        ):
            found[name] = result
    return found


def config6_hang_evidence(paths):
    """Evidence that the pallas-consensus KERNEL itself wedged on real
    hardware.  Returns the evidence dict or None.

    A whole-script timeout proves nothing — the tunnel may simply have
    died (``hw_queue.run_item``'s own docs say the partial stdout is
    the only way to tell those apart).  So this accepts only
    STAGE-LEVEL records: a ``consensus*`` probe line with
    ``timeout: true`` (from ``TPU_PROBE.json`` or embedded in an
    item's ``stdout_tail``, where neighboring probe lines prove the
    tunnel was alive around the hang), or a hard timeout of
    ``bench_config6`` itself (whose dead-tunnel mode is the distinct
    ``cpu-fallback`` rc, not a timeout).

    This is the VERDICT r2/r4 walkover rule made durable: a kernel
    whose decision measurement cannot complete on the chip loses to
    XLA by walkover, and the decision gets RECORDED instead of staying
    "pending" for another round (the round-4 journal held a >420 s
    Mosaic compile hang but PERF_DECISIONS.json carried no
    consensus_impl key at all)."""

    def probe_hang(entry, source):
        if (
            isinstance(entry, dict)
            and str(entry.get("probe", "")).startswith("consensus")
            and entry.get("timeout")
        ):
            return {
                "item": entry["probe"],
                "source": source,
                "timeout_after_s": entry.get("elapsed_s"),
            }
        return None

    for path, name, res in iter_result_entries(paths):
        source = os.path.basename(path)
        hit = probe_hang(res, source)
        if hit:
            return hit
        for line in res.get("stdout_tail") or []:
            try:
                hit = probe_hang(json.loads(line), f"{source}:{name}")
            except (ValueError, TypeError):
                hit = None
            if hit:
                return hit
        if name == "bench_config6" and res.get("rc") == "timeout":
            return {
                "item": name,
                "source": source,
                "timeout_after_s": res.get("seconds"),
            }
    return None


def load_flash_verdict(repo: str):
    """The on-TPU flash numerics verdict from FLASH_PARITY.json
    (``tools/flash_probe.py --parity-only``), or None when unmeasured.
    Only a verdict captured on the real chip counts — the interpret-mode
    CPU run cannot see Mosaic-specific numerics."""
    try:
        with open(os.path.join(repo, "FLASH_PARITY.json")) as f:
            parity = json.load(f)
        if isinstance(parity, dict) and parity.get("platform") == "tpu":
            return parity.get("verdict")
    except (OSError, ValueError):
        pass
    return None


def decide(results: dict, flash_verdict=None, c6_hang=None) -> tuple:
    """``(decisions, evidence)`` from qualifying TPU results only."""
    decisions = {}
    evidence = {}

    flagship = {
        variant: results[name]
        for name, variant in LOSSLESS_VARIANTS.items()
        if name in results
    }
    # config 0 may itself have routed through a variant — credit the
    # measurement to what actually ran, not to "dense"; never clobber a
    # dedicated (possibly better) measurement of the same variant.
    if "dense" in flagship:
        routed = flagship["dense"]["detail"].get("flagship_variant")
        if routed and routed != "dense":
            moved = flagship.pop("dense")
            if (
                routed not in flagship
                or flagship[routed]["value"] < moved["value"]
            ):
                flagship[routed] = moved
    # Flash on-HW numerics adjudication (VERDICT r4 item 2): the
    # flagship must not route through packed_flash while its only
    # on-silicon parity signal says "diverged".  "rounding-equivalent"
    # keeps packed_flash eligible, "diverged" excludes it, None =
    # unmeasured (eligible — the interpret-mode CPU tests remain the
    # only parity evidence).
    if flash_verdict:
        decisions["flash_numerics"] = flash_verdict
        if flash_verdict != "rounding-equivalent":
            flagship.pop("packed_flash", None)

    if flagship:
        best = max(flagship, key=lambda v: flagship[v]["value"])
        decisions["flagship_variant"] = best
        evidence["flagship_variant"] = {
            v: {
                "comments_per_sec": flagship[v]["value"],
                "mfu": flagship[v]["detail"].get("mfu_estimate"),
            }
            for v in flagship
        }
        if flash_verdict:
            evidence["flash_numerics"] = {
                "source": "FLASH_PARITY.json",
                "packed_flash_eligible": flash_verdict == "rounding-equivalent",
            }

    c6 = results.get("bench_config6")
    if c6:
        detail = c6["detail"]
        speedup = detail.get("pallas_vs_xla_speedup")
        wins = (
            not detail.get("pallas_hung")
            and speedup is not None
            and speedup > 1.0
            and detail.get("pallas_info", {}).get("essence_match_xla", False)
            and detail.get("pallas_kernel_active", False)
        )
        decisions["consensus_impl"] = "pallas" if wins else "xla"
        evidence["consensus_impl"] = {
            "pallas_vs_xla_speedup": speedup,
            "pallas_hung": detail.get("pallas_hung"),
            "hang_info": detail.get("pallas_info") if detail.get("pallas_hung") else None,
            "n_oracles": detail.get("n_oracles"),
        }
    elif c6_hang:
        # No clean measurement, but the measurement itself wedged on the
        # chip: xla wins by walkover and the decision is RECORDED — a
        # kernel that cannot complete its own decision bench at fleet
        # scale is not routable (two rounds of "pending" is enough).
        decisions["consensus_impl"] = "xla"
        evidence["consensus_impl"] = {
            "walkover": "measurement timed out on hardware",
            **c6_hang,
        }

    return decisions, evidence


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)

    paths = [
        os.path.join(REPO, "HW_QUEUE_RESULTS.json"),
        os.path.join(REPO, "HW_CAMPAIGN.json"),
    ]
    # MERGE with the committed record: a run that can only re-derive a
    # subset of the decisions (e.g. queue artifacts were reset and only
    # the hang evidence survives) must not silently drop a previously
    # measured flagship_variant back to bench.py's default.
    prior_decisions, prior_evidence = {}, {}
    try:
        with open(OUT) as f:
            prior = json.load(f)
        if isinstance(prior, dict):
            prior_evidence = (
                prior.get("evidence") if isinstance(prior.get("evidence"), dict) else {}
            )
            prior_decisions = {
                k: v
                for k, v in prior.items()
                if k in ("flagship_variant", "consensus_impl", "flash_numerics")
            }
    except (OSError, ValueError):
        pass

    # The committed flash_numerics verdict outlives FLASH_PARITY.json
    # (the journals feeding the routing are committed, the parity
    # artifact may not be): without this carry-over, a fresh checkout
    # would re-route the flagship through packed_flash while the merged
    # record still says "diverged" — a self-contradictory artifact.
    flash_verdict = load_flash_verdict(REPO) or prior_decisions.get(
        "flash_numerics"
    )
    results = latest_tpu_results(paths)
    decisions, evidence = decide(
        results,
        flash_verdict,
        config6_hang_evidence(paths + [os.path.join(REPO, "TPU_PROBE.json")]),
    )
    if not decisions:
        print("[decide_perf] no qualifying TPU measurements — nothing written")
        return 3

    merged = {**prior_decisions, **decisions}
    merged_evidence = {**prior_evidence, **evidence}
    # A merged record must not contradict itself (advisor round 5): a
    # PRIOR flagship_variant routed through packed_flash while the
    # merged flash_numerics verdict excludes it (a fresh "diverged"
    # verdict derived without fresh flagship measurements would
    # otherwise carry the stale routing forward).  Re-derive the
    # routing from the current results with the exclusion applied —
    # decide() already did exactly that — and when that produced no
    # flagship decision, DROP the key so bench.py's default routing
    # (never packed_flash) takes over.
    if (
        merged.get("flash_numerics")
        and merged["flash_numerics"] != "rounding-equivalent"
        and merged.get("flagship_variant") == "packed_flash"
    ):
        merged.pop("flagship_variant")
        merged_evidence["flagship_variant"] = {
            "dropped": (
                "prior flagship_variant 'packed_flash' contradicts the "
                f"merged flash_numerics verdict "
                f"{merged['flash_numerics']!r} and no qualifying "
                "measurement re-derived a routing"
            ),
            "prior": prior_evidence.get("flagship_variant"),
        }
        print(
            "[decide_perf] dropped prior flagship_variant=packed_flash: "
            "excluded by the merged flash_numerics verdict"
        )

    record = {
        **merged,
        "decided_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "rules": "tools/decide_perf.py (fixed; see module docstring)",
        "evidence": merged_evidence,
    }
    print(json.dumps(record, indent=1))
    if not args.dry_run:
        tmp = OUT + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1)
        os.replace(tmp, OUT)
        print(f"[decide_perf] wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
