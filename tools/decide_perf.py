#!/usr/bin/env python
"""Derive PERF_DECISIONS.json from measured hardware results.

Reads ``HW_CAMPAIGN.json`` (and/or ``HW_QUEUE_RESULTS.json``) and
applies the FIXED decision rules below, so the routing the flagship
bench and the serving paths follow is a reproducible function of
committed measurements — not an editorial choice:

- ``flagship_variant`` — the throughput argmax among the LOSSLESS
  end-to-end variants measured on the TPU backend: config 0 (dense),
  config 8 (packed), config 12 (packed x flash).  int8 configs are
  excluded: they trade accuracy and stay opt-in.
- ``consensus_impl`` — "pallas" iff config 6 measured the fused kernel
  on the TPU backend with ``pallas_vs_xla_speedup > 1``, no hang, and
  XLA-matching essence; "xla" otherwise (including by walkover when
  the Mosaic compile hung — the VERDICT r2 decision rule).

A decision is only derived from results whose ``detail.backend`` is
``"tpu"`` with no fallback/small-mode label; with no qualifying
measurements the tool writes nothing (exit 3) — the defaults in
``bench.py`` stay in force.

Usage::

    python tools/decide_perf.py [--dry-run]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "PERF_DECISIONS.json")

LOSSLESS_VARIANTS = {
    "bench_config0": "dense",
    "bench_config8": "packed",
    "bench_config12": "packed_flash",
}


def is_tpu_result(result: dict) -> bool:
    detail = result.get("detail", {})
    return (
        detail.get("backend") == "tpu"
        and not detail.get("backend_fallback")
        and not detail.get("small_mode")
    )


def latest_tpu_results(paths) -> dict:
    """``{item_name: result}`` — last qualifying TPU result per item
    across the given artifacts (later files win)."""
    found = {}
    for path in paths:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        for item in data.get("items", []):
            name = item.get("name", "")
            for res in item.get("results", [item]):
                result = res.get("result")
                # Only CLEAN attempts qualify: a bench that printed its
                # result line but exited nonzero (teardown crash, MFU
                # hard-fail) was rejected by the queue itself and must
                # not drive the committed routing.
                if res.get("rc") == 0 and result and is_tpu_result(result):
                    found[name] = result
    return found


def decide(results: dict) -> tuple:
    """``(decisions, evidence)`` from qualifying TPU results only."""
    decisions = {}
    evidence = {}

    flagship = {
        variant: results[name]
        for name, variant in LOSSLESS_VARIANTS.items()
        if name in results
    }
    # config 0 may itself have routed through a variant — credit the
    # measurement to what actually ran, not to "dense"; never clobber a
    # dedicated (possibly better) measurement of the same variant.
    if "dense" in flagship:
        routed = flagship["dense"]["detail"].get("flagship_variant")
        if routed and routed != "dense":
            moved = flagship.pop("dense")
            if (
                routed not in flagship
                or flagship[routed]["value"] < moved["value"]
            ):
                flagship[routed] = moved
    if flagship:
        best = max(flagship, key=lambda v: flagship[v]["value"])
        decisions["flagship_variant"] = best
        evidence["flagship_variant"] = {
            v: {
                "comments_per_sec": flagship[v]["value"],
                "mfu": flagship[v]["detail"].get("mfu_estimate"),
            }
            for v in flagship
        }

    c6 = results.get("bench_config6")
    if c6:
        detail = c6["detail"]
        speedup = detail.get("pallas_vs_xla_speedup")
        wins = (
            not detail.get("pallas_hung")
            and speedup is not None
            and speedup > 1.0
            and detail.get("pallas_info", {}).get("essence_match_xla", False)
            and detail.get("pallas_kernel_active", False)
        )
        decisions["consensus_impl"] = "pallas" if wins else "xla"
        evidence["consensus_impl"] = {
            "pallas_vs_xla_speedup": speedup,
            "pallas_hung": detail.get("pallas_hung"),
            "hang_info": detail.get("pallas_info") if detail.get("pallas_hung") else None,
            "n_oracles": detail.get("n_oracles"),
        }

    return decisions, evidence


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)

    results = latest_tpu_results(
        [
            os.path.join(REPO, "HW_QUEUE_RESULTS.json"),
            os.path.join(REPO, "HW_CAMPAIGN.json"),
        ]
    )
    decisions, evidence = decide(results)
    if not decisions:
        print("[decide_perf] no qualifying TPU measurements — nothing written")
        return 3

    record = {
        **decisions,
        "decided_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "rules": "tools/decide_perf.py (fixed; see module docstring)",
        "evidence": evidence,
    }
    print(json.dumps(record, indent=1))
    if not args.dry_run:
        tmp = OUT + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1)
        os.replace(tmp, OUT)
        print(f"[decide_perf] wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
