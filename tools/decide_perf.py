#!/usr/bin/env python
"""Derive PERF_DECISIONS.json from measured hardware results.

Reads ``HW_CAMPAIGN.json`` (and/or ``HW_QUEUE_RESULTS.json``) and
applies the FIXED decision rules below, so the routing the flagship
bench and the serving paths follow is a reproducible function of
committed measurements — not an editorial choice:

- ``flagship_variant`` — the throughput argmax among the LOSSLESS
  end-to-end variants measured on the TPU backend: config 0 (dense),
  config 8 (packed), config 12 (packed x flash).  int8 configs are
  excluded: they trade accuracy and stay opt-in.
- ``consensus_impl`` — "pallas" iff config 6 measured the fused kernel
  on the TPU backend with ``pallas_vs_xla_speedup > 1``, no hang, and
  XLA-matching essence; "xla" otherwise (including by walkover when
  the Mosaic compile hung — the VERDICT r2 decision rule).  The
  ``BENCH_CLAIMS_r06.json`` claim-cube grid is a second evidence
  source (ISSUE 11 satellite): a TPU-compiled grid point with a
  ``pallas_vs_xla_speedup > 1`` and matching essence flips to pallas;
  a grid holding only interpret/CPU points records the xla walkover
  with the artifact named — the committed r06 walkover flows through
  this machinery instead of a hand edit.
- ``commit_mode`` — the commit plane's RPC granularity
  (docs/RESILIENCE.md §batched-commits), from the committed
  ``BENCH_HOTPATH_r08.json`` host-overhead A/B: ``"batched"`` iff the
  bench measured fingerprint-identical runs, one batched RPC per
  claim-cycle (against N per-tx), and a ≥2× commit-stage speedup —
  HOST-side evidence, so unlike the device decisions it qualifies on
  the CPU container (the ISSUE 13 premise: host overhead is honestly
  measurable here); ``"per_tx"`` otherwise, with the failed check
  recorded as the blocker.
- ``claim_mesh`` — the 2-D (claim × oracle) dispatch mesh
  (docs/PARALLELISM.md §sharded-claims), from the
  ``BENCH_SHARD_r07.json`` sweep: the best-throughput mesh iff the
  sweep ran on TPU with ``parity_all_zero`` and ``scaling_verdict ==
  "scales"`` (≥1.5× at 1→4 devices, fixed total work); ``"none"``
  otherwise — including the honest-null CPU sweep (1-core container:
  simulated devices cannot add compute) and any parity breakage, with
  the blocker recorded as evidence.
- ``cost_plane`` — the cost-attribution plane's default
  (docs/OBSERVABILITY.md §cost-attribution), from the committed
  ``BENCH_OBS_r10.json`` A/B: ``"on"`` iff the plane's runs stayed
  fingerprint-identical to the off arm under open-loop load AND its
  measured p99 host step overhead is within the artifact's budget
  (≤ 5%) — host-side evidence like ``commit_mode``, so the CPU
  container qualifies; ``"off"`` otherwise with the blocker recorded.
  (Explicit ``SVOC_COST_PLANE`` / constructor pins always override the
  routed default.)
- ``cluster_replicas`` — the serving-fleet replica count
  (docs/CLUSTER.md), from the committed ``BENCH_CLUSTER_r11.json``
  fixed-total-work sweep: the best-QPS replica count iff the sweep ran
  on TPU-stamped hosts with clean fleet invariants (zero duplicate
  txs, zero unaccounted requests at every point) and
  ``scaling_verdict == "scales"`` (≥1.5× aggregate QPS at 1→4
  replicas); ``"1"`` otherwise — including the honest-null 1-core
  sweep (every replica thread time-slices the same core), with the
  blocker recorded as evidence (the BENCH_SHARD_r07 precedent).
- ``warmup_mode`` / ``compilation_cache`` — the compile plane
  (docs/PARALLELISM.md §compile-plane), from the committed
  ``BENCH_COLDSTART_r09.json`` A/B: ``"prewarm"`` iff the in-process
  prewarmed first dispatch beat cold by ≥5× with byte-identical
  numerics; ``"persistent"`` iff the ACROSS-RESTART leg also beat cold
  by ≥5× with zero fresh compiles after the restart.  Host-side
  evidence like ``commit_mode`` — compile latency is paid by the host
  XLA pipeline, so the CPU container qualifies; ``"none"``/``"off"``
  otherwise with the failed checks as the blocker.

A decision is only derived from results whose ``detail.backend`` is
``"tpu"`` with no fallback/small-mode label; with no qualifying
measurements the tool writes nothing (exit 3) — the defaults in
``bench.py`` stay in force.  (The grid-derived walkovers above are the
exception: they record the HONEST NULL — "measured, no win" — which
is itself a decision, per the r06 precedent.)

Usage::

    python tools/decide_perf.py [--dry-run]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "PERF_DECISIONS.json")

sys.path.insert(0, REPO)
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402
from bench import LOSSLESS_VARIANT_CONFIGS  # noqa: E402

# {item_name: variant} derived from bench.py's single mapping so the
# decision rules and the replay routing can never drift.
LOSSLESS_VARIANTS = {
    f"bench_config{cfg}": variant
    for variant, cfg in LOSSLESS_VARIANT_CONFIGS.items()
}


def is_tpu_result(result: dict) -> bool:
    detail = result.get("detail", {})
    return (
        detail.get("backend") == "tpu"
        and not detail.get("backend_fallback")
        and not detail.get("small_mode")
    )


def iter_result_entries(paths):
    """Yield ``(path, item_name, res_dict)`` for every result entry in
    the given queue/campaign artifacts, tolerating both journal shapes
    (items with a ``results`` list vs flat one-shot items) and skipping
    malformed entries instead of crashing — the single journal-walking
    loop shared by every evidence scan in this module."""
    for path in paths:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        items = data.get("items") if isinstance(data, dict) else data
        for item in items or []:
            if not isinstance(item, dict):
                continue
            name = item.get("name", item.get("probe", ""))
            results = item.get("results")
            for res in results if isinstance(results, list) else [item]:
                if isinstance(res, dict):
                    yield path, name, res


def latest_tpu_results(paths) -> dict:
    """``{item_name: result}`` — last qualifying TPU result per item
    across the given artifacts (later files win)."""
    found = {}
    for _path, name, res in iter_result_entries(paths):
        result = res.get("result")
        # Only CLEAN attempts qualify: a bench that printed its result
        # line but exited nonzero (teardown crash, MFU hard-fail) was
        # rejected by the queue itself, and a campaign_replay line is
        # recycled data, not a capture — neither may drive the
        # committed routing.
        if (
            res.get("rc") == 0
            and isinstance(result, dict)
            and is_tpu_result(result)
            and not result.get("detail", {}).get("replayed_from")
        ):
            found[name] = result
    return found


def config6_hang_evidence(paths):
    """Evidence that the pallas-consensus KERNEL itself wedged on real
    hardware.  Returns the evidence dict or None.

    A whole-script timeout proves nothing — the tunnel may simply have
    died (``hw_queue.run_item``'s own docs say the partial stdout is
    the only way to tell those apart).  So this accepts only
    STAGE-LEVEL records: a ``consensus*`` probe line with
    ``timeout: true`` (from ``TPU_PROBE.json`` or embedded in an
    item's ``stdout_tail``, where neighboring probe lines prove the
    tunnel was alive around the hang), or a hard timeout of
    ``bench_config6`` itself (whose dead-tunnel mode is the distinct
    ``cpu-fallback`` rc, not a timeout).

    This is the VERDICT r2/r4 walkover rule made durable: a kernel
    whose decision measurement cannot complete on the chip loses to
    XLA by walkover, and the decision gets RECORDED instead of staying
    "pending" for another round (the round-4 journal held a >420 s
    Mosaic compile hang but PERF_DECISIONS.json carried no
    consensus_impl key at all)."""

    def probe_hang(entry, source):
        if (
            isinstance(entry, dict)
            and str(entry.get("probe", "")).startswith("consensus")
            and entry.get("timeout")
        ):
            return {
                "item": entry["probe"],
                "source": source,
                "timeout_after_s": entry.get("elapsed_s"),
            }
        return None

    for path, name, res in iter_result_entries(paths):
        source = os.path.basename(path)
        hit = probe_hang(res, source)
        if hit:
            return hit
        for line in res.get("stdout_tail") or []:
            try:
                hit = probe_hang(json.loads(line), f"{source}:{name}")
            except (ValueError, TypeError):
                hit = None
            if hit:
                return hit
        if name == "bench_config6" and res.get("rc") == "timeout":
            return {
                "item": name,
                "source": source,
                "timeout_after_s": res.get("seconds"),
            }
    return None


def load_grid(path):
    """Load a bench grid artifact (``BENCH_CLAIMS_r06.json`` /
    ``BENCH_SHARD_r07.json``: ``{"artifact", "platform"/"date",
    "items": [bench lines], ...}``) or None when absent/malformed."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or not isinstance(data.get("items"), list):
        return None
    return data


def load_hotpath_grid(path):
    """Load the host-overhead A/B artifact (``BENCH_HOTPATH_r08.json``:
    a flat ``{"checks", "commit", ...}`` record, not an items grid) or
    None when absent/malformed."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or not isinstance(data.get("checks"), dict):
        return None
    return data


def grid_is_tpu(grid: dict) -> bool:
    """A grid measured on real chips: every successful item's stamped
    ``device_topology.platform`` is ``"tpu"`` (pre-topology artifacts
    fall back to the artifact-level platform string)."""
    topos = [
        it.get("detail", {}).get("device_topology")
        for it in grid["items"]
        if isinstance(it, dict) and isinstance(it.get("detail"), dict)
    ]
    if any(isinstance(t, dict) for t in topos):
        return all(
            isinstance(t, dict) and t.get("platform") == "tpu"
            for t in topos
        )
    return str(grid.get("platform", "")).strip().lower().startswith("tpu")


def claims_grid_consensus_evidence(grid):
    """``(decision_or_None, evidence)`` from the claim-cube A/B grid.

    A TPU-compiled point with a real speedup and matching essence flips
    to pallas (best point wins); anything else — interpret mode, CPU,
    hangs — is the recorded xla walkover.  Returns ``(None, None)``
    when there is no grid."""
    if grid is None:
        return None, None
    wins = []
    modes = set()
    for item in grid["items"]:
        if not isinstance(item, dict):
            continue
        ab = item.get("detail", {}).get("pallas_ab")
        if not isinstance(ab, dict):
            continue
        modes.add(ab.get("pallas_mode"))
        speedup = ab.get("pallas_vs_xla_speedup")
        if (
            grid_is_tpu(grid)
            and ab.get("pallas_mode") == "compiled"
            and not ab.get("pallas_hung")
            and speedup is not None
            and speedup > 1.0
            and ab.get("pallas_info", {}).get("essence_match_xla", False)
        ):
            wins.append((speedup, item))
    if wins:
        speedup, item = max(wins, key=lambda w: w[0])
        return "pallas", {
            "source": "claims-grid",
            "pallas_vs_xla_speedup": speedup,
            "shape": item.get("metric"),
        }
    return "xla", {
        "source": "claims-grid",
        "walkover": (
            "no TPU-compiled pallas win in the claims grid "
            f"(modes seen: {sorted(str(m) for m in modes)})"
        ),
        "tpu_grid": grid_is_tpu(grid),
    }


def shard_grid_mesh_decision(grid):
    """``(decision_or_None, evidence)`` for the ``claim_mesh`` routing
    from the sharded-cube sweep.  Routing through a mesh needs ALL of:
    a TPU sweep, bitwise parity on every point, and the ≥1.5× 1→4
    scaling verdict; everything else records ``"none"`` with the
    sweep's own verdict/blocker as evidence (the honest null IS the
    decision — a 1-core CPU container cannot measure scaling, and the
    unsharded default must stay routed until real chips overturn it)."""
    if grid is None:
        return None, None
    parity = bool(grid.get("parity_all_zero"))
    verdict = grid.get("scaling_verdict")
    scaling = grid.get("scaling_vs_1x1") or {}
    evidence = {
        "source": grid.get("artifact", "shard-grid"),
        "parity_all_zero": parity,
        "scaling_verdict": verdict,
        "scaling_vs_1x1": scaling,
        "scaling_blocker": grid.get("scaling_blocker"),
        "tpu_grid": grid_is_tpu(grid),
    }
    if grid_is_tpu(grid) and parity and verdict == "scales":
        best = None
        for item in grid["items"]:
            if not isinstance(item, dict) or item.get("rc") != 0:
                continue
            detail = item.get("detail", {})
            cps = detail.get("sharded_claims_per_s")
            if cps and (best is None or cps > best[0]):
                best = (cps, detail.get("mesh"))
        if best and best[1] and best[1] != "1x1":
            evidence["best_mesh_claims_per_s"] = best[0]
            return str(best[1]), evidence
    return "none", evidence


def cluster_replicas_decision(grid):
    """``(decision_or_None, evidence)`` for the ``cluster_replicas``
    routing from the fleet scaling bench (``BENCH_CLUSTER_r11.json``).
    Routing more than one serving replica needs ALL of: a TPU-stamped
    sweep, clean fleet invariants (zero duplicate txs, zero unaccounted
    requests at every point), and the ≥1.5× 1→4 ``"scales"`` verdict;
    everything else records ``"1"`` with the sweep's own verdict and
    blocker as evidence — the honest null IS the decision (the 1-core
    container time-slices every replica onto the same core, the
    BENCH_SHARD_r07 precedent)."""
    if grid is None:
        return None, None
    clean = bool(grid.get("fleet_invariants_clean"))
    verdict = grid.get("scaling_verdict")
    scaling = grid.get("scaling_vs_1_replica") or {}
    evidence = {
        "source": grid.get("artifact", "cluster-bench"),
        "fleet_invariants_clean": clean,
        "scaling_verdict": verdict,
        "scaling_vs_1_replica": scaling,
        "scaling_blocker": grid.get("scaling_blocker"),
        "tpu_grid": grid_is_tpu(grid),
    }
    if grid_is_tpu(grid) and clean and verdict == "scales":
        best = None
        for item in grid["items"]:
            if not isinstance(item, dict) or item.get("rc") != 0:
                continue
            detail = item.get("detail", {})
            qps = item.get("value")
            if qps and (best is None or qps > best[0]):
                best = (qps, detail.get("n_replicas"))
        if best and best[1] and int(best[1]) > 1:
            evidence["best_replicas_qps"] = best[0]
            return str(int(best[1])), evidence
    return "1", evidence


def hotpath_commit_decision(grid):
    """``(decision_or_None, evidence)`` for the ``commit_mode`` routing
    from the host-overhead A/B (``bench_hotpath.py``).  Host-side
    measurement: no TPU gate — the bench runs WAL-attached on the
    serving container's own commit plane, which is exactly where the
    win (or its absence) applies."""
    if grid is None:
        return None, None
    checks = grid.get("checks")
    if not isinstance(checks, dict):
        return None, None
    commit = grid.get("commit") if isinstance(grid.get("commit"), dict) else {}
    evidence = {
        "source": grid.get("artifact", "BENCH_HOTPATH"),
        "commit_speedup": commit.get("speedup"),
        "rpcs_per_claim_cycle": commit.get("rpcs_per_claim_cycle"),
        "fingerprint_identical": checks.get("fingerprint_identical"),
        "host_measured": True,
    }
    required = (
        "fingerprint_identical",
        "baseline_rpcs_per_claim_cycle_is_n",
        "batched_rpcs_per_claim_cycle_is_1",
        "commit_speedup_ge_2",
    )
    failed = [k for k in required if not checks.get(k)]
    if not failed:
        return "batched", evidence
    evidence["blocker"] = f"failed checks: {failed}"
    return "per_tx", evidence


def load_obs_grid(path):
    """Load the cost-plane overhead A/B artifact
    (``BENCH_OBS_r12.json``, falling back to the pre-fleet-arm
    ``BENCH_OBS_r10.json``: a flat ``{"checks", "arms",
    "p99_overhead", ...}`` record) or None when absent/malformed — the
    same shape-tolerant contract as :func:`load_hotpath_grid`."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or not isinstance(data.get("checks"), dict):
        return None
    return data


def obs_cost_decision(grid):
    """``(decision_or_None, evidence)`` for the ``cost_plane`` routing
    from the overhead A/B (``bench_obs.py``).  Host-side measurement
    like ``commit_mode`` — the plane's cost IS host work (perf_counter
    reads, ring appends), so the CPU container qualifies.  ``"on"``
    needs fingerprint identity across both arms (replay invisibility
    under load) and the measured p99 overhead within the artifact's
    budget; anything else routes ``"off"`` with the blocker named."""
    if grid is None:
        return None, None
    checks = grid.get("checks")
    if not isinstance(checks, dict):
        return None, None
    evidence = {
        "source": grid.get("artifact", "BENCH_OBS"),
        "p99_overhead": grid.get("p99_overhead"),
        "p50_overhead": grid.get("p50_overhead"),
        "overhead_budget": grid.get("overhead_budget"),
        "fingerprints_identical": checks.get(
            "fingerprints_identical_across_arms"
        ),
        "host_measured": True,
    }
    required = (
        "fingerprints_identical_across_arms",
        "both_arms_measured",
        "overhead_finite",
    )
    failed = [k for k in required if not checks.get(k)]
    if not failed and grid.get("within_budget"):
        return "on", evidence
    evidence["blocker"] = (
        f"failed checks: {failed}"
        if failed
        else (
            f"p99 overhead {grid.get('p99_overhead')} exceeds budget "
            f"{grid.get('overhead_budget')}"
        )
    )
    return "off", evidence


def load_coldstart_grid(path):
    """Load the cold-start A/B artifact (``BENCH_COLDSTART_r09.json``:
    a flat ``{"checks", "legs", "speedups_vs_cold", ...}`` record) or
    None when absent/malformed — the same shape-tolerant contract as
    :func:`load_hotpath_grid`."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or not isinstance(data.get("checks"), dict):
        return None
    return data


def coldstart_decisions(grid):
    """``({decision_key: value}, {decision_key: evidence})`` for the
    compile-plane routing from the cold-start A/B
    (``bench_coldstart.py``).  Host-side measurement like
    ``commit_mode`` — no TPU gate: compile latency is host work, and
    the on-chip (Mosaic) compile cost is strictly larger, so a
    CPU-measured win is a lower bound (the artifact's
    ``tpu_compile_cost: null`` honest-null stands until the campaign
    measures the chip)."""
    if grid is None:
        return {}, {}
    checks = grid.get("checks")
    if not isinstance(checks, dict):
        return {}, {}
    speedups = (
        grid.get("speedups_vs_cold")
        if isinstance(grid.get("speedups_vs_cold"), dict)
        else {}
    )
    source = grid.get("artifact", "BENCH_COLDSTART")
    decisions, evidence = {}, {}

    warm_required = ("numerics_identical_across_legs", "prewarmed_speedup_ge_5")
    warm_failed = [k for k in warm_required if not checks.get(k)]
    warm_evidence = {
        "source": source,
        "prewarm_speedup": speedups.get("prewarm"),
        "host_measured": True,
    }
    if not warm_failed:
        decisions["warmup_mode"] = "prewarm"
    else:
        decisions["warmup_mode"] = "none"
        warm_evidence["blocker"] = f"failed checks: {warm_failed}"
    evidence["warmup_mode"] = warm_evidence

    cache_required = warm_required + (
        "restart_speedup_ge_5",
        "zero_fresh_compiles_after_restart",
    )
    cache_failed = [k for k in cache_required if not checks.get(k)]
    cache_evidence = {
        "source": source,
        "restart_speedup": speedups.get("restart"),
        "restart_nowarm_speedup": speedups.get("restart_nowarm"),
        "fresh_compiles_after_restart": (
            grid.get("legs", {})
            .get("restart", {})
            .get("fresh_compiles_during_dispatch")
        ),
        "host_measured": True,
    }
    if not cache_failed:
        decisions["compilation_cache"] = "persistent"
    else:
        decisions["compilation_cache"] = "off"
        cache_evidence["blocker"] = f"failed checks: {cache_failed}"
    evidence["compilation_cache"] = cache_evidence
    return decisions, evidence


def load_flash_verdict(repo: str):
    """The on-TPU flash numerics verdict from FLASH_PARITY.json
    (``tools/flash_probe.py --parity-only``), or None when unmeasured.
    Only a verdict captured on the real chip counts — the interpret-mode
    CPU run cannot see Mosaic-specific numerics."""
    try:
        with open(os.path.join(repo, "FLASH_PARITY.json")) as f:
            parity = json.load(f)
        if isinstance(parity, dict) and parity.get("platform") == "tpu":
            return parity.get("verdict")
    except (OSError, ValueError):
        pass
    return None


def decide(
    results: dict,
    flash_verdict=None,
    c6_hang=None,
    claims_grid=None,
    shard_grid=None,
    hotpath_grid=None,
    coldstart_grid=None,
    obs_grid=None,
    cluster_grid=None,
) -> tuple:
    """``(decisions, evidence)`` from qualifying TPU results (plus the
    grid walkover rules — module docstring)."""
    decisions = {}
    evidence = {}

    flagship = {
        variant: results[name]
        for name, variant in LOSSLESS_VARIANTS.items()
        if name in results
    }
    # config 0 may itself have routed through a variant — credit the
    # measurement to what actually ran, not to "dense"; never clobber a
    # dedicated (possibly better) measurement of the same variant.
    if "dense" in flagship:
        routed = flagship["dense"]["detail"].get("flagship_variant")
        if routed and routed != "dense":
            moved = flagship.pop("dense")
            if (
                routed not in flagship
                or flagship[routed]["value"] < moved["value"]
            ):
                flagship[routed] = moved
    # Flash on-HW numerics adjudication (VERDICT r4 item 2): the
    # flagship must not route through packed_flash while its only
    # on-silicon parity signal says "diverged".  "rounding-equivalent"
    # keeps packed_flash eligible, "diverged" excludes it, None =
    # unmeasured (eligible — the interpret-mode CPU tests remain the
    # only parity evidence).
    if flash_verdict:
        decisions["flash_numerics"] = flash_verdict
        if flash_verdict != "rounding-equivalent":
            flagship.pop("packed_flash", None)

    if flagship:
        best = max(flagship, key=lambda v: flagship[v]["value"])
        decisions["flagship_variant"] = best
        evidence["flagship_variant"] = {
            v: {
                "comments_per_sec": flagship[v]["value"],
                "mfu": flagship[v]["detail"].get("mfu_estimate"),
            }
            for v in flagship
        }
        if flash_verdict:
            evidence["flash_numerics"] = {
                "source": "FLASH_PARITY.json",
                "packed_flash_eligible": flash_verdict == "rounding-equivalent",
            }

    c6 = results.get("bench_config6")
    if c6:
        detail = c6["detail"]
        speedup = detail.get("pallas_vs_xla_speedup")
        wins = (
            not detail.get("pallas_hung")
            and speedup is not None
            and speedup > 1.0
            and detail.get("pallas_info", {}).get("essence_match_xla", False)
            and detail.get("pallas_kernel_active", False)
        )
        decisions["consensus_impl"] = "pallas" if wins else "xla"
        evidence["consensus_impl"] = {
            "pallas_vs_xla_speedup": speedup,
            "pallas_hung": detail.get("pallas_hung"),
            "hang_info": detail.get("pallas_info") if detail.get("pallas_hung") else None,
            "n_oracles": detail.get("n_oracles"),
        }
    elif c6_hang:
        # No clean measurement, but the measurement itself wedged on the
        # chip: xla wins by walkover and the decision is RECORDED — a
        # kernel that cannot complete its own decision bench at fleet
        # scale is not routable (two rounds of "pending" is enough).
        decisions["consensus_impl"] = "xla"
        evidence["consensus_impl"] = {
            "walkover": "measurement timed out on hardware",
            **c6_hang,
        }
    else:
        # Third evidence source: the claim-cube A/B grid (ISSUE 11
        # satellite) — a TPU-compiled win flips to pallas; an
        # interpret/CPU-only grid records the xla walkover.
        grid_impl, grid_evidence = claims_grid_consensus_evidence(
            claims_grid
        )
        if grid_impl:
            decisions["consensus_impl"] = grid_impl
            evidence["consensus_impl"] = grid_evidence

    mesh_decision, mesh_evidence = shard_grid_mesh_decision(shard_grid)
    if mesh_decision is not None:
        decisions["claim_mesh"] = mesh_decision
        evidence["claim_mesh"] = mesh_evidence

    commit_decision, commit_evidence = hotpath_commit_decision(hotpath_grid)
    if commit_decision is not None:
        decisions["commit_mode"] = commit_decision
        evidence["commit_mode"] = commit_evidence

    cold_decisions, cold_evidence = coldstart_decisions(coldstart_grid)
    decisions.update(cold_decisions)
    evidence.update(cold_evidence)

    obs_decision, obs_evidence = obs_cost_decision(obs_grid)
    if obs_decision is not None:
        decisions["cost_plane"] = obs_decision
        evidence["cost_plane"] = obs_evidence

    replicas_decision, replicas_evidence = cluster_replicas_decision(
        cluster_grid
    )
    if replicas_decision is not None:
        decisions["cluster_replicas"] = replicas_decision
        evidence["cluster_replicas"] = replicas_evidence

    return decisions, evidence


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)

    paths = [
        os.path.join(REPO, "HW_QUEUE_RESULTS.json"),
        os.path.join(REPO, "HW_CAMPAIGN.json"),
    ]
    # MERGE with the committed record: a run that can only re-derive a
    # subset of the decisions (e.g. queue artifacts were reset and only
    # the hang evidence survives) must not silently drop a previously
    # measured flagship_variant back to bench.py's default.  The same
    # protection applies to consensus_impl below: a claims-grid
    # WALKOVER (committed CPU/interpret grid — always present, never a
    # measurement) fills absence only and must not demote a prior
    # measured routing.
    prior_decisions, prior_evidence = {}, {}
    try:
        with open(OUT) as f:
            prior = json.load(f)
        if isinstance(prior, dict):
            prior_evidence = (
                prior.get("evidence") if isinstance(prior.get("evidence"), dict) else {}
            )
            prior_decisions = {
                k: v
                for k, v in prior.items()
                if k
                in (
                    "flagship_variant",
                    "consensus_impl",
                    "flash_numerics",
                    "claim_mesh",
                    "commit_mode",
                    "warmup_mode",
                    "compilation_cache",
                    "cost_plane",
                    "cluster_replicas",
                )
            }
    except (OSError, ValueError):
        pass

    # The committed flash_numerics verdict outlives FLASH_PARITY.json
    # (the journals feeding the routing are committed, the parity
    # artifact may not be): without this carry-over, a fresh checkout
    # would re-route the flagship through packed_flash while the merged
    # record still says "diverged" — a self-contradictory artifact.
    flash_verdict = load_flash_verdict(REPO) or prior_decisions.get(
        "flash_numerics"
    )
    results = latest_tpu_results(paths)
    decisions, evidence = decide(
        results,
        flash_verdict,
        config6_hang_evidence(paths + [os.path.join(REPO, "TPU_PROBE.json")]),
        claims_grid=load_grid(os.path.join(REPO, "BENCH_CLAIMS_r06.json")),
        shard_grid=load_grid(os.path.join(REPO, "BENCH_SHARD_r07.json")),
        hotpath_grid=load_hotpath_grid(
            os.path.join(REPO, "BENCH_HOTPATH_r08.json")
        ),
        coldstart_grid=load_coldstart_grid(
            os.path.join(REPO, "BENCH_COLDSTART_r09.json")
        ),
        obs_grid=(
            load_obs_grid(os.path.join(REPO, "BENCH_OBS_r12.json"))
            or load_obs_grid(os.path.join(REPO, "BENCH_OBS_r10.json"))
        ),
        cluster_grid=load_grid(os.path.join(REPO, "BENCH_CLUSTER_r11.json")),
    )
    if (
        "consensus_impl" in prior_decisions
        and evidence.get("consensus_impl", {}).get("source")
        == "claims-grid"
        and "walkover" in evidence.get("consensus_impl", {})
    ):
        # The grid walkover is a statement of NO evidence — when queue
        # artifacts were reset but the committed record still carries a
        # measured decision, the measurement stands.
        decisions.pop("consensus_impl")
        evidence.pop("consensus_impl")
        print(
            "[decide_perf] claims-grid walkover suppressed: the prior "
            "measured consensus_impl stands"
        )

    if not decisions:
        print("[decide_perf] no qualifying TPU measurements — nothing written")
        return 3

    merged = {**prior_decisions, **decisions}
    merged_evidence = {**prior_evidence, **evidence}
    # A merged record must not contradict itself (advisor round 5): a
    # PRIOR flagship_variant routed through packed_flash while the
    # merged flash_numerics verdict excludes it (a fresh "diverged"
    # verdict derived without fresh flagship measurements would
    # otherwise carry the stale routing forward).  Re-derive the
    # routing from the current results with the exclusion applied —
    # decide() already did exactly that — and when that produced no
    # flagship decision, DROP the key so bench.py's default routing
    # (never packed_flash) takes over.
    if (
        merged.get("flash_numerics")
        and merged["flash_numerics"] != "rounding-equivalent"
        and merged.get("flagship_variant") == "packed_flash"
    ):
        merged.pop("flagship_variant")
        merged_evidence["flagship_variant"] = {
            "dropped": (
                "prior flagship_variant 'packed_flash' contradicts the "
                f"merged flash_numerics verdict "
                f"{merged['flash_numerics']!r} and no qualifying "
                "measurement re-derived a routing"
            ),
            "prior": prior_evidence.get("flagship_variant"),
        }
        print(
            "[decide_perf] dropped prior flagship_variant=packed_flash: "
            "excluded by the merged flash_numerics verdict"
        )

    record = {
        **merged,
        "decided_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "rules": "tools/decide_perf.py (fixed; see module docstring)",
        "evidence": merged_evidence,
    }
    print(json.dumps(record, indent=1))
    if not args.dry_run:
        atomic_write_json(OUT, record)
        print(f"[decide_perf] wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
