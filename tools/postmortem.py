#!/usr/bin/env python
"""postmortem — dump a flight-recorder debug bundle on demand.

Assembles everything the process-wide observability singletons hold —
journal tail, span ring, metrics registry, environment — into one
atomically-written JSON bundle (``svoc_tpu.utils.postmortem``).  Run it
from a REPL/debug session next to a live framework process, or import
:func:`svoc_tpu.utils.postmortem.build_bundle` and pass the session for
the resilience/config sections.

Usage::

    python tools/postmortem.py [--out-dir .] [--trigger manual]
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out-dir", default=".")
    p.add_argument("--trigger", default="manual")
    p.add_argument(
        "--events-tail", type=int, default=512, help="journal events to embed"
    )
    p.add_argument(
        "--spans-tail", type=int, default=256, help="spans to embed"
    )
    args = p.parse_args(argv)

    from svoc_tpu.utils.postmortem import build_bundle

    path = build_bundle(
        out_dir=args.out_dir,
        trigger=args.trigger,
        events_tail=args.events_tail,
        spans_tail=args.spans_tail,
    )
    print(f"postmortem bundle written: {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
