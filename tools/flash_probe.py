#!/usr/bin/env python
"""Honest flash-vs-dense attention timings on the real chip.

Amortized protocol (dispatch N unique-input calls, host-fetch only the
last — see tools/dispatch_probe3.py): ``block_until_ready`` does not
prove execution on the tunneled backend, so the round-2
``TPU_PROBE.json`` flash/dense numbers were meaningless.  Writes
FLASH_PROBE.json.

``--parity-only`` runs the NUMERICS adjudication alone (VERDICT r4
item 2) and writes ``FLASH_PARITY.json``.  The round-4 evidence —
``max_abs_diff == 0.015625`` (= 2^-6) at every probed shape, and the
``flash512 match_dense: false`` at a naive atol of 2e-3 — is exactly
the signature of bf16 OUTPUT rounding, not a kernel bug:

- both kernels accumulate in f32 on the MXU
  (``pallas_attention.py``: every dot has
  ``preferred_element_type=f32``; the XLA dense path accumulates bf16
  dots in f32) and cast the final output to bf16, so each is a
  faithful-rounding of the true f32 result to within O(eps_bf16) of
  the output scale, where eps_bf16 = 2^-8 (7 mantissa bits);
- the DENSE reference additionally rounds the softmax probabilities to
  bf16 before the PV matmul (``ring_attention.py:71``,
  ``p.astype(v.dtype)``) — the flash kernel keeps P in f32
  (``pallas_attention.py:114``), so where they differ, flash is the
  MORE accurate of the two;
- a flash-vs-dense diff of 1-2 ulp at output magnitude ~2 (ulp = 2^-6
  on [2,4)) is therefore EXPECTED; asserting atol 2e-3 < 1 ulp between
  two independently-rounded bf16 results was a tolerance bug in the
  probe, not a numerics failure in the kernel.

The adjudication therefore compares BOTH bf16 kernels against an
f32-truth dense attention and passes iff flash's error stays within
the dtype-aware bound ``BOUND_ULPS x eps_bf16 x max|truth|`` and is no
worse than the dense path's own error (modulo one rounding).  The
interpret path (same dtype chain, different op order) already
CORROBORATES the verdict: at (256, 128) it reproduces the on-HW
flash-vs-dense diff of 0.015625 exactly, with err_flash = 0.0078 <
err_dense = 0.020 against the f32 truth (both within the 0.045 bound)
— pinned in ``tests/test_pallas_attention.py``.  Mosaic-SPECIFIC
numerics still need silicon — this probe is that check, queued as the
campaign's ``flash_parity`` decision item.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

import jax
import jax.numpy as jnp
import numpy as np


def amortized_ms(step, n=16):
    float(np.asarray(jnp.sum(step(0))))  # warm/compile
    t0 = time.perf_counter()
    h = None
    for i in range(n):
        h = step(i + 1)
    float(np.asarray(jnp.sum(h)))
    return (time.perf_counter() - t0) / n * 1e3


EPS_BF16 = 2.0 ** -8  # 7 explicit mantissa bits -> rounding unit 2^-8
#: Shapes the parity adjudication probes ((batch, seq) at 12 heads x
#: d 64): the flagship shape and the mid-length one the round-4
#: flash512 signal came from.  Module-level so tests can shrink them.
PARITY_SHAPES = ((256, 128), (8, 512))
# Headroom over a single final-cast rounding: the f32 accumulation
# order differs between the two kernels (blocked online softmax vs one
# monolithic softmax), contributing a few more ulps of f32-level noise
# scaled up to the bf16 grid by the final cast.
BOUND_ULPS = 4.0


def parity_only():
    """Dtype-aware on-HW numerics adjudication -> FLASH_PARITY.json."""
    import numpy as np

    from svoc_tpu.ops.pallas_attention import flash_attention
    from svoc_tpu.parallel.ring_attention import dense_attention_reference

    # The axon sitecustomize pins the TPU plugin regardless of env
    # vars; honor an explicit CPU request BEFORE the first device probe
    # or a dead tunnel hangs this process (verify-skill gotcha).
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    if platform != "tpu":
        # The adjudication only means anything on Mosaic — an interpret
        # -mode verdict would mark the campaign item done while
        # decide_perf ignores the artifact (platform gate).  Emit the
        # bench-shaped fallback line so hw_queue demotes this run to
        # "cpu-fallback" (attempt refunded, item retried on the next
        # alive window) and write no artifact.
        print(json.dumps({
            "metric": "flash numerics parity (on-HW adjudication)",
            "value": None,
            "unit": "verdict",
            "vs_baseline": None,
            "detail": {
                "backend": platform,
                "backend_fallback": "parity adjudication requires the real chip",
            },
        }), flush=True)
        return 0
    entries = []
    h, d = 12, 64
    for b, t in PARITY_SHAPES:
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(jax.random.fold_in(key, 7), (b, t, h, d), jnp.bfloat16)
        mask = jnp.ones((b, t), jnp.int32)
        truth = np.asarray(
            dense_attention_reference(
                q.astype(jnp.float32),
                q.astype(jnp.float32),
                q.astype(jnp.float32),
                mask,
            )
        )
        dense_bf16 = np.asarray(
            jax.jit(lambda x: dense_attention_reference(x, x, x, mask))(q)
        ).astype(np.float32)
        flash_bf16 = np.asarray(
            jax.jit(
                lambda x: flash_attention(x, x, x, mask, block_q=256, block_k=256)
            )(q)
        ).astype(np.float32)
        scale = float(np.max(np.abs(truth)))
        bound = BOUND_ULPS * EPS_BF16 * scale
        err_flash = float(np.max(np.abs(flash_bf16 - truth)))
        err_dense = float(np.max(np.abs(dense_bf16 - truth)))
        flash_vs_dense = float(np.max(np.abs(flash_bf16 - dense_bf16)))
        ok = err_flash <= bound and err_flash <= 2.0 * err_dense + EPS_BF16 * scale
        entries.append({
            "b": b, "t": t, "h": h, "d": d,
            "out_scale": scale,
            "bound": bound,
            "err_flash_vs_f32_truth": err_flash,
            "err_dense_vs_f32_truth": err_dense,
            "flash_vs_dense": flash_vs_dense,
            "flash_within_bound": ok,
        })
        print(json.dumps(entries[-1]), flush=True)
    verdict = {
        "platform": platform,
        "eps_bf16": EPS_BF16,
        "bound_ulps": BOUND_ULPS,
        "entries": entries,
        "verdict": (
            "rounding-equivalent"
            if all(e["flash_within_bound"] for e in entries)
            else "diverged"
        ),
        "note": (
            "flash keeps softmax P in f32 (pallas_attention.py:114); the "
            "dense reference rounds P to bf16 before PV "
            "(ring_attention.py:71) — where they differ, flash is the "
            "more accurate; see module docstring for the full bound"
        ),
        "captured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
    }
    atomic_write_json("FLASH_PARITY.json", verdict)
    print(json.dumps({"verdict": verdict["verdict"]}), flush=True)
    # A completed adjudication is a SUCCESS whichever way it lands —
    # "diverged" is a valid decision outcome (it routes the flagship
    # back to packed×dense via decide_perf), not an item failure for
    # the campaign to burn retries on.
    return 0


def main():
    from svoc_tpu.ops.pallas_attention import flash_attention
    from svoc_tpu.parallel.ring_attention import dense_attention_reference

    results = []

    def persist():
        """Flush after every stage: the 2026-07-30 on-chip run hung in
        this probe (suspect: the FA-2 backward Mosaic compile) and lost
        every number because the file was written only at the end."""
        atomic_write_json("FLASH_PROBE.json", results)

    h, d = 12, 64
    for b, t in ((256, 128), (8, 512), (8, 2048), (2, 8192)):
        key = jax.random.PRNGKey(0)
        qs = [
            jax.random.normal(jax.random.fold_in(key, i), (b, t, h, d), jnp.bfloat16)
            for i in range(4)
        ]
        mask = jnp.ones((b, t), jnp.int32)
        dense = jax.jit(lambda q: dense_attention_reference(q, q, q, mask))
        flash = jax.jit(
            lambda q: flash_attention(q, q, q, mask, block_q=256, block_k=256)
        )

        entry = {"b": b, "t": t, "h": h, "d": d}
        t0 = time.perf_counter()
        out_f = flash(qs[0])
        float(np.asarray(jnp.sum(out_f)))
        entry["flash_compile_s"] = round(time.perf_counter() - t0, 2)
        out_d = dense(qs[0])
        entry["max_abs_diff"] = float(
            jnp.max(jnp.abs(out_f.astype(jnp.float32) - out_d.astype(jnp.float32)))
        )
        entry["dense_ms"] = round(amortized_ms(lambda i: dense(qs[i % 4]), n=12), 3)
        entry["flash_ms"] = round(amortized_ms(lambda i: flash(qs[i % 4]), n=12), 3)
        entry["speedup"] = round(entry["dense_ms"] / entry["flash_ms"], 3)
        results.append(entry)
        persist()  # forward numbers are safe before the bwd compile

        # Backward (FlashAttention-2 custom VJP vs autodiff-of-dense):
        # grad of sum(out) wrt q/k/v, dq summed as the fetch handle.
        dense_grad = jax.jit(
            jax.grad(lambda q: jnp.sum(
                dense_attention_reference(q, q, q, mask).astype(jnp.float32)
            ))
        )
        flash_grad = jax.jit(
            jax.grad(lambda q: jnp.sum(
                flash_attention(
                    q, q, q, mask, block_q=256, block_k=256
                ).astype(jnp.float32)
            ))
        )
        t0 = time.perf_counter()
        g_f = flash_grad(qs[0])
        float(np.asarray(jnp.sum(g_f)))
        entry["flash_bwd_compile_s"] = round(time.perf_counter() - t0, 2)
        g_d = dense_grad(qs[0])
        entry["bwd_max_abs_diff"] = float(
            jnp.max(jnp.abs(g_f.astype(jnp.float32) - g_d.astype(jnp.float32)))
        )
        entry["dense_bwd_ms"] = round(
            amortized_ms(lambda i: dense_grad(qs[i % 4]), n=12), 3
        )
        entry["flash_bwd_ms"] = round(
            amortized_ms(lambda i: flash_grad(qs[i % 4]), n=12), 3
        )
        entry["bwd_speedup"] = round(
            entry["dense_bwd_ms"] / entry["flash_bwd_ms"], 3
        )
        print(json.dumps(entry), flush=True)
        persist()


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--parity-only",
        action="store_true",
        help="numerics adjudication only -> FLASH_PARITY.json",
    )
    ns = ap.parse_args()
    sys.exit(parity_only() if ns.parity_only else (main() or 0))
