#!/usr/bin/env python
"""Honest flash-vs-dense attention timings on the real chip.

Amortized protocol (dispatch N unique-input calls, host-fetch only the
last — see tools/dispatch_probe3.py): ``block_until_ready`` does not
prove execution on the tunneled backend, so the round-2
``TPU_PROBE.json`` flash/dense numbers were meaningless.  Writes
FLASH_PROBE.json.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def amortized_ms(step, n=16):
    float(np.asarray(jnp.sum(step(0))))  # warm/compile
    t0 = time.perf_counter()
    h = None
    for i in range(n):
        h = step(i + 1)
    float(np.asarray(jnp.sum(h)))
    return (time.perf_counter() - t0) / n * 1e3


def main():
    from svoc_tpu.ops.pallas_attention import flash_attention
    from svoc_tpu.parallel.ring_attention import dense_attention_reference

    results = []

    def persist():
        """Flush after every stage: the 2026-07-30 on-chip run hung in
        this probe (suspect: the FA-2 backward Mosaic compile) and lost
        every number because the file was written only at the end."""
        tmp = "FLASH_PROBE.json.tmp"
        with open(tmp, "w") as fh:
            json.dump(results, fh, indent=1)
        os.replace(tmp, "FLASH_PROBE.json")

    h, d = 12, 64
    for b, t in ((256, 128), (8, 512), (8, 2048), (2, 8192)):
        key = jax.random.PRNGKey(0)
        qs = [
            jax.random.normal(jax.random.fold_in(key, i), (b, t, h, d), jnp.bfloat16)
            for i in range(4)
        ]
        mask = jnp.ones((b, t), jnp.int32)
        dense = jax.jit(lambda q: dense_attention_reference(q, q, q, mask))
        flash = jax.jit(
            lambda q: flash_attention(q, q, q, mask, block_q=256, block_k=256)
        )

        entry = {"b": b, "t": t, "h": h, "d": d}
        t0 = time.perf_counter()
        out_f = flash(qs[0])
        float(np.asarray(jnp.sum(out_f)))
        entry["flash_compile_s"] = round(time.perf_counter() - t0, 2)
        out_d = dense(qs[0])
        entry["max_abs_diff"] = float(
            jnp.max(jnp.abs(out_f.astype(jnp.float32) - out_d.astype(jnp.float32)))
        )
        entry["dense_ms"] = round(amortized_ms(lambda i: dense(qs[i % 4]), n=12), 3)
        entry["flash_ms"] = round(amortized_ms(lambda i: flash(qs[i % 4]), n=12), 3)
        entry["speedup"] = round(entry["dense_ms"] / entry["flash_ms"], 3)
        results.append(entry)
        persist()  # forward numbers are safe before the bwd compile

        # Backward (FlashAttention-2 custom VJP vs autodiff-of-dense):
        # grad of sum(out) wrt q/k/v, dq summed as the fetch handle.
        dense_grad = jax.jit(
            jax.grad(lambda q: jnp.sum(
                dense_attention_reference(q, q, q, mask).astype(jnp.float32)
            ))
        )
        flash_grad = jax.jit(
            jax.grad(lambda q: jnp.sum(
                flash_attention(
                    q, q, q, mask, block_q=256, block_k=256
                ).astype(jnp.float32)
            ))
        )
        t0 = time.perf_counter()
        g_f = flash_grad(qs[0])
        float(np.asarray(jnp.sum(g_f)))
        entry["flash_bwd_compile_s"] = round(time.perf_counter() - t0, 2)
        g_d = dense_grad(qs[0])
        entry["bwd_max_abs_diff"] = float(
            jnp.max(jnp.abs(g_f.astype(jnp.float32) - g_d.astype(jnp.float32)))
        )
        entry["dense_bwd_ms"] = round(
            amortized_ms(lambda i: dense_grad(qs[i % 4]), n=12), 3
        )
        entry["flash_bwd_ms"] = round(
            amortized_ms(lambda i: flash_grad(qs[i % 4]), n=12), 3
        )
        entry["bwd_speedup"] = round(
            entry["dense_bwd_ms"] / entry["flash_bwd_ms"], 3
        )
        print(json.dumps(entry), flush=True)
        persist()


if __name__ == "__main__":
    main()
