"""Live-reconfiguration chaos gate: transactional re-pin as CI
(``make reconfig-smoke``; docs/RECONFIG.md, docs/RESILIENCE.md
§fault-surface).

Three run families over :func:`svoc_tpu.cluster.reconfig_scenario
.run_reconfig_scenario`, all seeded and byte-reproducible:

1. **Committed transition, twice** — a 3-replica × 6-claim fleet under
   traffic, with a rolling mesh/commit-mode/spec re-pin applied
   mid-schedule.  The controller's traffic hook fires a probe at every
   stage boundary, so the DEFERRED path (held replica's traffic parked
   at the router, replayed on release) is in the replayed stream.
   Asserted: replay identity (fleet + per-claim fingerprints byte-
   identical across the two runs, INCLUDING the epoch transition),
   epoch chain advanced exactly once, lineage continuity for every
   re-pinned claim, zero shed (every probe deferred — never
   ``unavailable``), zero duplicate txs, zero unaccounted requests.

2. **Abort at every fault point** — a smaller fleet, one run per
   ``reconfig.*`` point with an injected ``error``, each compared
   against a baseline run with the identical schedule AND the identical
   (never-firing) event list but no plan.  Asserted: the abort report
   is typed, the rollback leaves the fleet fingerprint byte-identical
   to never having attempted the plan, and zero requests were dropped
   or duplicated.

3. **Coverage** — all five ``reconfig.*`` points witnessed in the
   durable fired logs across the abort family.

Usage::

    python tools/reconfig_smoke.py [--seed 0] [--out RECONFIG_SMOKE.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform —
# tools/soak.py measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import sys  # noqa: E402
import tempfile  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from svoc_tpu.durability.faultspace import (  # noqa: E402
    FaultEvent,
    read_fired_log,
)
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

N_REPLICAS = 3
N_CLAIMS = 6
TOTAL_STEPS = 10
ARRIVALS_PER_STEP = 8
RECONFIG_AT_STEP = 4

RECONFIG_POINTS = (
    "reconfig.prepare",
    "reconfig.post_drain",
    "reconfig.post_ship",
    "reconfig.pre_repin",
    "reconfig.pre_resume",
)

#: The committed transition: flip the WAL commit mode and re-spec one
#: claim (wider oracle panel) in one transaction — a knob re-pin AND a
#: spec-diff carry through the same epoch boundary.
def _plan(n_oracles: int, dimension: int) -> dict:
    from svoc_tpu.fabric.registry import ClaimSpec
    from svoc_tpu.utils.checkpoint import claim_spec_to_dict

    return {
        "consensus_impl": None,
        "mesh": None,
        "commit_mode": "batched",
        "claims": {
            "c0": claim_spec_to_dict(
                ClaimSpec(
                    claim_id="c0",
                    n_oracles=n_oracles + 2,
                    dimension=dimension,
                )
            )
        },
        "add_replicas": [],
        "remove_replicas": [],
    }


def run_committed(seed: int) -> dict:
    from svoc_tpu.cluster.reconfig_scenario import run_reconfig_scenario

    workdir = tempfile.mkdtemp(prefix="reconfig-smoke-")
    result = run_reconfig_scenario(
        workdir,
        seed=seed,
        n_replicas=N_REPLICAS,
        n_claims=N_CLAIMS,
        total_steps=TOTAL_STEPS,
        arrivals_per_step=ARRIVALS_PER_STEP,
        reconfig_at_step=RECONFIG_AT_STEP,
        plan=_plan(7, 6),
    )
    result["workdir"] = workdir
    result["fired_log"] = read_fired_log(os.path.join(workdir, "fired.jsonl"))
    return result


def run_abort_pair(seed: int, point: str) -> tuple:
    """(baseline, aborted) — identical schedule and event list; only
    the plan differs, and the abort must erase it."""
    from svoc_tpu.cluster.reconfig_scenario import run_reconfig_scenario

    events = [FaultEvent(point=point, nth=1, action="error")]

    def run(with_plan: bool) -> dict:
        workdir = tempfile.mkdtemp(prefix="reconfig-abort-")
        result = run_reconfig_scenario(
            workdir,
            seed=seed,
            n_replicas=2,
            n_claims=3,
            total_steps=6,
            arrivals_per_step=4,
            reconfig_at_step=2,
            plan=_plan(7, 6) if with_plan else None,
            traffic_probes=False,
            events=list(events),
        )
        result["workdir"] = workdir
        result["fired_log"] = read_fired_log(
            os.path.join(workdir, "fired.jsonl")
        )
        return result

    return run(with_plan=False), run(with_plan=True)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", default="RECONFIG_SMOKE.json")
    args = parser.parse_args()

    checks = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        checks.append({"check": name, "ok": bool(ok), "detail": detail})
        print(f"[{'PASS' if ok else 'FAIL'}] {name}" + (f" — {detail}" if detail else ""))

    # -- family 1: committed transition, twice ------------------------------
    first = run_committed(args.seed)
    second = run_committed(args.seed)

    check(
        "transition committed under traffic",
        (first["reconfig"] or {}).get("status") == "committed",
        f"epoch {first['reconfig_epoch']}",
    )
    check(
        "fleet fingerprint byte-identical across committed runs",
        first["fleet_fingerprint"] == second["fleet_fingerprint"],
        first["fleet_fingerprint"][:16],
    )
    check(
        "per-claim fingerprints byte-identical across committed runs",
        all(
            first["claims"][cid]["fingerprint"]
            == second["claims"][cid]["fingerprint"]
            for cid in first["claims"]
        ),
        f"{len(first['claims'])} claims",
    )
    check(
        "epoch chain advanced exactly once, plan fingerprint recorded",
        first["reconfig_epoch"] == 1
        and len(first["epoch_chain"]) == 1
        and first["epoch_chain"][0]["plan"]
        == first["reconfig"]["plan_fingerprint"],
        (first["epoch_chain"][0]["plan"][:16] if first["epoch_chain"] else ""),
    )
    repinned = first["reconfig"]["replicas"]
    check(
        "lineage continuity for every re-pinned claim",
        bool(repinned)
        and all(
            c["continuity"]
            for rep in repinned.values()
            for c in rep["claims"].values()
        ),
        f"{sum(len(rep['claims']) for rep in repinned.values())} claims "
        f"across {len(repinned)} replicas",
    )
    check(
        "spec-diff claim carried (fresh session, lineage fields kept)",
        any(
            rep["claims"].get("c0", {}).get("carried")
            for rep in repinned.values()
        ),
    )
    deferred = [
        p for p in first["probes"] if p["response"].get("status") == "deferred"
    ]
    check(
        "mid-transition traffic deferred, never shed",
        len(deferred) > 0
        and first["cluster_counters"]["cluster_unavailable"] == 0,
        f"{len(deferred)} deferred, 0 sheds",
    )
    check(
        "every deferred request released at commit",
        first["reconfig"]["deferred_released"] == len(deferred),
        f"{first['reconfig']['deferred_released']} released",
    )
    check(
        "zero duplicate txs through the epoch boundary",
        first["duplicate_txs"] == 0 and second["duplicate_txs"] == 0,
        f"{first['duplicate_txs']} + {second['duplicate_txs']}",
    )
    requests = first["requests"]
    check(
        "zero unaccounted admitted requests fleet-wide",
        requests["unaccounted"] == 0
        and second["requests"]["unaccounted"] == 0,
        f"admitted={requests['admitted']:.0f} "
        f"completed={requests['completed']:.0f} "
        f"dropped={requests['dropped']:.0f}",
    )
    check(
        "pending-config universe prewarmed in PREPARE",
        (first["reconfig"]["prewarm"] or {}).get("keys", 0) > 0,
        str(first["reconfig"]["prewarm"]),
    )

    # -- family 2: abort at every fault point -------------------------------
    fired_points = set(first["fired_log"]["fired"])
    aborts = {}
    for point in RECONFIG_POINTS:
        baseline, aborted = run_abort_pair(args.seed, point)
        aborts[point] = {
            "status": (aborted["reconfig"] or {}).get("status"),
            "phase": (aborted["reconfig"] or {}).get("phase"),
            "identical": aborted["fleet_fingerprint"]
            == baseline["fleet_fingerprint"],
            "unaccounted": aborted["requests"]["unaccounted"],
            "duplicate_txs": aborted["duplicate_txs"],
        }
        fired_points |= set(aborted["fired_log"]["fired"])
        check(
            f"abort @ {point} rolls back to the never-attempted fingerprint",
            aborts[point]["status"] == "aborted"
            and aborts[point]["identical"]
            and aborts[point]["unaccounted"] == 0
            and aborts[point]["duplicate_txs"] == 0,
            f"phase={aborts[point]['phase']}",
        )

    # -- family 3: coverage --------------------------------------------------
    missing = [p for p in RECONFIG_POINTS if p not in fired_points]
    check(
        "all reconfig fault points witnessed in the durable fired logs",
        not missing,
        f"missing={missing}" if missing else f"{len(RECONFIG_POINTS)} points",
    )

    ok = all(c["ok"] for c in checks)
    artifact = {
        "artifact": "reconfig_smoke",
        "seed": args.seed,
        "config": {
            "n_replicas": N_REPLICAS,
            "n_claims": N_CLAIMS,
            "total_steps": TOTAL_STEPS,
            "arrivals_per_step": ARRIVALS_PER_STEP,
            "reconfig_at_step": RECONFIG_AT_STEP,
            "plan": _plan(7, 6),
        },
        "checks": checks,
        "reconfig": first["reconfig"],
        "epoch_chain": first["epoch_chain"],
        "aborts": aborts,
        "requests": first["requests"],
        "cluster_counters": first["cluster_counters"],
        "fleet_fingerprint": first["fleet_fingerprint"],
        "ok": ok,
    }
    atomic_write_json(args.out, artifact)
    print(f"{'PASS' if ok else 'FAIL'}: reconfig smoke -> {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
