"""Byzantine robustness certification: ``ROBUSTNESS_CERT.json`` as a gate.

Runs the empirical breakdown-point sweep
(:mod:`svoc_tpu.robustness.certify`) for BOTH consensus configurations
and the seeded Byzantine chaos scenario
(:func:`svoc_tpu.resilience.chaos.run_byzantine_scenario`) twice, then
asserts the ISSUE-4 acceptance surface:

- every implemented attack strategy tolerates a colluder fraction
  ≥ ``n_failing/N`` at bounded essence deviation (constrained AND
  unconstrained estimators);
- the Byzantine scenario replays fingerprint-identically, quarantines
  every injected malformed vector with zero false quarantines, never
  duplicates a tx, and votes the colluding cluster + the injector out
  through the contract's replacement flow.

``--smoke`` shrinks the grid to a seconds-scale CI gate
(``make robustness-smoke``, wired into presnapshot/verify);
the default grid is the full certificate (``make robustness-cert``).

Usage::

    python tools/robustness_cert.py [--smoke] [--seed 0]
        [--out ROBUSTNESS_CERT.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform, so
# go through jax.config too — tools/soak.py measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import dataclasses  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402
import time  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402


def _jsonable_sweep(sweep):
    out = dict(sweep)
    out["cells"] = [dataclasses.asdict(c) for c in sweep["cells"]]
    out["benign_deviation"] = {
        str(k): v for k, v in sweep["benign_deviation"].items()
    }
    return out


def run(seed: int, smoke: bool) -> dict:
    import jax

    from svoc_tpu.consensus.kernel import ConsensusConfig
    from svoc_tpu.resilience.chaos import run_byzantine_scenario
    from svoc_tpu.robustness.certify import breakdown_sweep, certificate

    n_oracles, n_failing = 8, 2
    counts = list(range(0, 5))  # 0 … N/2 colluders
    if smoke:
        trials, magnitudes_c, magnitudes_u = 8, [0.45], [5.0]
    else:
        trials = 64
        #: real-unit offsets along the target direction: inside the
        #: honest spread, at the hull edge, and saturating the domain.
        magnitudes_c = [0.2, 0.45, 0.9]
        magnitudes_u = [2.5, 5.0, 10.0]  # fractions of max_spread=10

    key = jax.random.PRNGKey(seed)
    k_con, k_unc = jax.random.split(key)
    sweeps = {}
    certs = {}
    for name, cfg, mags, bound in (
        (
            "constrained",
            ConsensusConfig(n_failing=n_failing, constrained=True),
            magnitudes_c,
            0.05,
        ),
        (
            "unconstrained",
            ConsensusConfig(
                n_failing=n_failing, constrained=False, max_spread=10.0
            ),
            magnitudes_u,
            0.5,
        ),
    ):
        sweep = breakdown_sweep(
            k_con if cfg.constrained else k_unc,
            cfg,
            n_oracles=n_oracles,
            colluder_counts=counts,
            magnitudes=mags,
            n_trials=trials,
        )
        sweeps[name] = sweep
        certs[name] = certificate(sweep, bound_abs=bound)

    byz = run_byzantine_scenario(seed)
    byz_replay = run_byzantine_scenario(seed)

    checks = {
        "constrained_certified": certs["constrained"]["certified"],
        "unconstrained_certified": certs["unconstrained"]["certified"],
        "byzantine_replayable": byz["fingerprint"] == byz_replay["fingerprint"],
        "all_injections_quarantined": byz["missed_injections"] == 0
        and byz["injections"] > 0,
        "zero_false_quarantines": byz["false_quarantines"] == 0,
        "quarantine_reasons_as_expected": byz["reason_mismatches"] == 0,
        "colluders_voted_out": byz["colluders_voted_out"],
        "injector_voted_out": byz["injector_voted_out"],
        "no_duplicate_txs": byz["duplicate_txs"] == 0,
        "consensus_held": byz["consensus_active"] and byz["essence_in_band"],
    }
    return {
        "seed": seed,
        "mode": "smoke" if smoke else "full",
        "checks": checks,
        "ok": all(checks.values()),
        "certificates": certs,
        "byzantine": byz,
        "byzantine_replay_fingerprint": byz_replay["fingerprint"],
        "sweeps": {k: _jsonable_sweep(v) for k, v in sweeps.items()},
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)
    out_path = args.out or (
        "ROBUSTNESS_SMOKE.json" if args.smoke else "ROBUSTNESS_CERT.json"
    )

    t0 = time.monotonic()
    artifact = run(args.seed, args.smoke)
    artifact["elapsed_s"] = round(time.monotonic() - t0, 2)
    atomic_write_json(out_path, artifact)
    summary = {
        "robustness_cert": "ok" if artifact["ok"] else "FAILED",
        "mode": artifact["mode"],
        "checks": artifact["checks"],
        "tolerated": {
            name: {
                a: d["tolerated_fraction"]
                for a, d in cert["attacks"].items()
            }
            for name, cert in artifact["certificates"].items()
        },
        "elapsed_s": artifact["elapsed_s"],
    }
    print(json.dumps(summary), flush=True)
    return 0 if artifact["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
