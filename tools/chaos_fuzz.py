"""Deterministic fault-space fuzzer gate: ``make chaos-fuzz-smoke``
(docs/RESILIENCE.md §fault-surface).

Explores the declared fault surface
(:mod:`svoc_tpu.durability.faultspace`) with seed-drawn kill/restart
schedules (:mod:`svoc_tpu.durability.fuzz`): per seed, a crash+recover
subprocess chain in one work directory — SIGKILL at the Nth firing of a
named point, torn writes, injected chain faults, ``per_tx`` vs
``batched`` commit mode, restart storms (a second kill mid-recovery) —
then the invariant oracles over the recovered artifacts and a full
same-seed rerun asserting byte-identical recovered fingerprints.

The gate FAILS when:

- any invariant oracle trips (duplicate txs, lost commits, unclosed
  cycles, unknown slots with a reachable backend, codec divergences,
  replay divergence, harness errors) — the failing plan is
  **auto-shrunk** and written into the regression corpus
  (``tests/fixtures/chaos_corpus/`` by default) for tier-1 to replay;
- any ``"fuzz"``-smoke fault point never fired across the whole seed
  budget (a durable boundary escaped exploration — 100 % declared-point
  coverage is the acceptance bar);
- the dedicated **felt-wire segment** (VERDICT item 9: a fault-free
  ``commit_mode="batched"`` soak through the batched adapter's
  ``encoding="felt"`` plane) reports any codec divergence.

Children are deliberately jax-free (~1 s each — the point of the light
durable-plane harness; the full fabric/serving stack keeps its own kill
matrix in ``make crash-smoke``), so the default 32-seed budget runs in
roughly a minute or two on this 1-core container.  ``--seeds N`` is the
deep mode for detached runs.

Usage::

    python tools/chaos_fuzz.py [--seeds 32] [--jobs 3] [--out CHAOS_FUZZ.json]
    python tools/chaos_fuzz.py --seeds 512 --base-dir /tmp/fuzz-deep   # deep
    python tools/chaos_fuzz.py --child DIR --plan PLAN.json --phase N  # internal
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from svoc_tpu.durability import faultspace, fuzz  # noqa: E402
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

DEFAULT_SEEDS = 32


def child_main(args) -> int:
    with open(args.plan) as f:
        plan = fuzz.FuzzPlan.from_dict(json.load(f))
    result = fuzz.run_fuzz_child(args.child, plan, args.phase)
    atomic_write_json(os.path.join(args.child, fuzz.RESULT_NAME), result)
    return 0


def _seed_summary(seed: int, checked: dict) -> dict:
    run = checked["run"]
    result = run.get("result") or {}
    return {
        "seed": seed,
        "plan": checked["plan"],
        "phases": [
            {"phase": p["phase"], "killed": p["killed"]}
            for p in run["phases"]
        ],
        "violations": checked["violations"],
        "replay_identical": checked["replay_identical"],
        "fingerprint": result.get("fingerprint"),
        "duplicate_txs": result.get("duplicate_txs"),
        "codec_divergences": result.get("codec_divergences"),
        "fired": checked["fired"]["fired"],
        "actions": checked["fired"]["actions"],
        # Reconstructed from the durable action log (a killed phase's
        # remaining events die with its controller, so the surviving
        # child's in-memory view alone would under-report).
        "unfired_events": run.get("unexecuted_events", []),
    }


def felt_segment(base_dir: str) -> dict:
    """VERDICT item 9: a fault-free batched soak — every commit rides
    the one-RPC batched adapter, whose backend applies with
    ``encoding="felt"`` — asserting zero codec divergences on the felt
    wire (plus the standard oracles and replay identity)."""
    plan = fuzz.FuzzPlan(
        seed=9_000_000, commit_mode="batched", cycles=8,
        label="felt_soak",
    )
    checked = fuzz.run_and_check(plan, os.path.join(base_dir, "felt-soak"))
    result = checked["run"].get("result") or {}
    return {
        "plan": checked["plan"],
        "violations": checked["violations"],
        "replay_identical": checked["replay_identical"],
        "codec_divergences": result.get("codec_divergences"),
        "predictions_committed": sum(
            c.get("predictions", 0)
            for c in (result.get("chain") or {}).values()
        ),
        "ok": not checked["violations"]
        and result.get("codec_divergences") == 0,
    }


def shrink_and_record(
    seed: int, checked: dict, base_dir: str, corpus_dir: str, budget: int
) -> dict:
    """Auto-shrink a failing plan to a minimal repro and write it into
    the regression corpus (``expect="pass"`` — the entry goes green
    once the bug is fixed, and tier-1 replays it forever)."""
    plan = fuzz.FuzzPlan.from_dict(checked["plan"])
    need_replay = any(
        v.startswith("replay_divergence") for v in checked["violations"]
    )
    trial_no = [0]

    def fails(candidate: fuzz.FuzzPlan) -> bool:
        trial_no[0] += 1
        trial_dir = os.path.join(
            base_dir, f"shrink-s{seed}-t{trial_no[0]:03d}"
        )
        return bool(
            fuzz.run_and_check(
                candidate, trial_dir, replay=need_replay
            )["violations"]
        )

    shrunk = fuzz.shrink_plan(plan, fails, budget=budget)
    # Record the SHRUNK plan's OWN violations: shrinking accepts any
    # failing neighbor, so the minimal repro can reproduce a different
    # failure class than the original seed did — the corpus entry must
    # pin what the stored plan actually does.
    final = fuzz.run_and_check(
        shrunk["plan"],
        os.path.join(base_dir, f"shrink-s{seed}-final"),
        replay=need_replay,
    )
    captured = final["violations"] or checked["violations"]
    path = fuzz.write_corpus_entry(
        corpus_dir,
        shrunk["plan"],
        captured,
        shrunk_from=plan,
        notes=f"auto-shrunk from seed {seed} in {shrunk['trials']} trials "
        f"by tools/chaos_fuzz.py (original seed's violations: "
        f"{checked['violations']}); commit this entry WITH the fix so "
        f"tier-1 replays it green",
    )
    return {
        "seed": seed,
        "corpus_entry": path,
        "trials": shrunk["trials"],
        "shrunk_plan": shrunk["plan"].as_dict(),
    }


def main(argv=None) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seeds", type=int, default=DEFAULT_SEEDS)
    p.add_argument("--jobs", type=int, default=3)
    p.add_argument("--out", default="CHAOS_FUZZ.json")
    p.add_argument("--base-dir", default=None,
                   help="work area (default: fresh temp dir)")
    p.add_argument(
        "--corpus-dir",
        default=os.path.join(repo_root, "tests", "fixtures", "chaos_corpus"),
    )
    p.add_argument("--shrink-budget", type=int, default=12)
    p.add_argument("--child", default=None, help="(internal) phase workdir")
    p.add_argument("--plan", default=None, help="(internal) plan JSON path")
    p.add_argument("--phase", type=int, default=0)
    args = p.parse_args(argv)
    if args.child is not None:
        return child_main(args)

    surface = faultspace.load_surface()
    fuzz_surface = fuzz.fuzz_points(surface)
    base = args.base_dir or tempfile.mkdtemp(prefix="chaos-fuzz-")
    os.makedirs(base, exist_ok=True)

    plans = {seed: fuzz.draw_plan(seed, surface) for seed in
             range(args.seeds)}
    summaries = {}
    with concurrent.futures.ThreadPoolExecutor(args.jobs) as pool:
        futures = {
            seed: pool.submit(
                fuzz.run_and_check, plan, os.path.join(base, f"seed-{seed}")
            )
            for seed, plan in plans.items()
        }
        for seed, future in futures.items():
            summaries[seed] = _seed_summary(seed, future.result())

    felt = felt_segment(base)

    # Coverage: every "fuzz"-smoke point must have fired somewhere.
    coverage = {
        name: sorted(
            s["seed"] for s in summaries.values() if name in s["fired"]
        )
        for name in fuzz_surface
    }
    never_fired = sorted(n for n, seeds in coverage.items() if not seeds)

    failing = {
        seed: s for seed, s in summaries.items() if s["violations"]
    }
    shrunk_entries = []
    for seed, s in sorted(failing.items()):
        shrunk_entries.append(
            shrink_and_record(
                seed, s, base, args.corpus_dir, args.shrink_budget
            )
        )

    checks = {
        # The ISSUE 14 acceptance bar is absolute: a --seeds 4 dev run
        # honestly FAILS this check rather than passing vacuously.
        "seeds_explored_at_least_32": len(summaries) >= 32,
        "declared_fuzz_points_all_fired": not never_fired,
        "zero_invariant_violations": not failing,
        "zero_duplicate_txs": all(
            (s["duplicate_txs"] or 0) == 0 for s in summaries.values()
        ),
        "same_seed_rerun_fingerprints_identical": all(
            s["replay_identical"] is True for s in summaries.values()
        ),
        "felt_segment_zero_codec_divergences": felt["ok"],
    }
    ok = all(checks.values())
    artifact = {
        "seeds": args.seeds,
        "surface": {
            name: {
                "owner": spec.owner,
                "invariant": spec.invariant,
                "actions": list(spec.actions),
                "smokes": list(spec.smokes),
                "modes": list(spec.modes),
                "stage": spec.stage,
                "fired_in_seeds": coverage.get(name),
            }
            for name, spec in sorted(surface.items())
        },
        "coverage_never_fired": never_fired,
        "felt_segment": felt,
        "checks": checks,
        "ok": ok,
        "violations": {
            seed: s["violations"] for seed, s in sorted(failing.items())
        },
        "shrunk": shrunk_entries,
        "runs": [summaries[seed] for seed in sorted(summaries)],
    }
    atomic_write_json(args.out, artifact)
    for name, passed in sorted(checks.items()):
        print(f"  {'PASS' if passed else 'FAIL'}  {name}")
    if never_fired:
        print(f"  never fired: {never_fired}")
    for entry in shrunk_entries:
        print(
            f"  seed {entry['seed']} FAILED -> shrunk repro written to "
            f"{entry['corpus_entry']} ({entry['trials']} trials); commit "
            f"it with the fix so tier-1 replays it green"
        )
    n_actions = sum(len(s["actions"]) for s in summaries.values())
    print(
        f"chaos-fuzz {'OK' if ok else 'FAILED'}: {len(summaries)} seeds, "
        f"{len(fuzz_surface)} fuzz-surface points "
        f"({len(surface)} declared), {n_actions} fault actions executed, "
        f"felt segment {'clean' if felt['ok'] else 'DIVERGED'} "
        f"-> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
