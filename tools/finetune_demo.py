"""One real fine-tune, end to end (VERDICT r3 item 8).

The trainer was parity-tested but had never trained ON anything; this
demo gives it a task and drives the full loop the way a user would:

1. **Task**: synthetic keyword sentiment — each text mixes neutral
   filler with keywords from up to 3 of the 6 tracked emotion families
   (optimism, anger, annoyance, excitement, nervousness, remorse); the
   multi-hot label marks which families appear.  Learnable, non-trivial
   (multi-label, variable length, shared filler), and needs no dataset
   download (the image has no egress).
2. **Training**: the tiny encoder via
   :func:`svoc_tpu.train.trainer.make_sharded_train_step` on a GSPMD
   ``data × model`` mesh (8 virtual CPU devices — the same path a v5e-8
   runs), AdamW, to a target eval metric (macro-F1 over the 6 tracked
   labels).
3. **Checkpoint/resume** (:mod:`svoc_tpu.utils.checkpoint`, orbax):
   a mid-run checkpoint; (a) restoring it on the SAME mesh and
   replaying the remaining steps must reproduce the uninterrupted
   final params exactly; (b) restoring it onto a DIFFERENT mesh
   layout (data×model 4×2 → 2×4) must yield identical parameter
   values re-sharded, and training must continue from them.

Writes ``FINETUNE_r04.json``: loss curve, eval F1 before/after, both
restore checks.  Exit 0 iff final macro-F1 ≥ ``--target-f1`` and both
restore checks pass.

Usage::

    python tools/finetune_demo.py [--steps 60] [--batch 32]
        [--target-f1 0.9] [--out FINETUNE_r04.json]
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import argparse  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402
import tempfile  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

#: keyword families for the 6 tracked labels (order = TRACKED_LABELS).
FAMILIES = {
    "optimism": ["hopeful", "promising", "bright", "improving", "upbeat"],
    "anger": ["furious", "outraged", "livid", "seething", "enraged"],
    "annoyance": ["irritating", "tedious", "nagging", "grating", "bothersome"],
    "excitement": ["thrilled", "stoked", "electrifying", "exhilarating"],
    "nervousness": ["anxious", "jittery", "uneasy", "worried", "tense"],
    "remorse": ["sorry", "regretful", "ashamed", "apologetic", "guilty"],
}
FILLER = (
    "the build system compiles modules into artifacts and the scheduler "
    "queues jobs across nodes while the database commits transactions to "
    "replicated logs and the parser emits tokens for the compiler backend"
).split()


def make_dataset(rng, n, tracked_indices, n_labels):
    """(texts, labels [n, n_labels] multi-hot) for the keyword task."""
    fams = list(FAMILIES.values())
    texts, labels = [], np.zeros((n, n_labels), np.float32)
    for i in range(n):
        k = int(rng.integers(1, 4))  # 1..3 families present
        present = rng.choice(len(fams), size=k, replace=False)
        words = list(rng.choice(FILLER, size=int(rng.integers(4, 9))))
        for f in present:
            words += list(
                rng.choice(fams[f], size=int(rng.integers(2, 5)))
            )
            labels[i, tracked_indices[f]] = 1.0
        rng.shuffle(words)
        texts.append(" ".join(words))
    return texts, labels


def macro_f1(pred: np.ndarray, truth: np.ndarray) -> float:
    """Macro-F1 over label columns (pred/truth multi-hot)."""
    f1s = []
    for j in range(pred.shape[1]):
        tp = float(np.sum((pred[:, j] == 1) & (truth[:, j] == 1)))
        fp = float(np.sum((pred[:, j] == 1) & (truth[:, j] == 0)))
        fn = float(np.sum((pred[:, j] == 0) & (truth[:, j] == 1)))
        if tp + fp + fn == 0:
            continue  # label absent from eval slice
        f1s.append(2 * tp / max(2 * tp + fp + fn, 1e-9))
    return float(np.mean(f1s)) if f1s else 0.0


def tree_max_abs_diff(a, b) -> float:
    leaves = zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    return max(
        float(np.max(np.abs(np.asarray(x) - np.asarray(y)))) for x, y in leaves
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=240)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--target-f1", type=float, default=0.9)
    p.add_argument("--eval-n", type=int, default=128)
    p.add_argument("--out", default="FINETUNE_r04.json")
    p.add_argument(
        "--zero1",
        action="store_true",
        help=(
            "shard optimizer state over the data axis "
            "(arXiv:2004.13336 / ZeRO-1); same update math, "
            "~1/D at-rest optimizer memory per replica"
        ),
    )
    args = p.parse_args(argv)

    import optax
    from jax.sharding import Mesh

    from svoc_tpu.models.configs import TINY_TEST
    from svoc_tpu.models.encoder import SentimentEncoder, init_params
    from svoc_tpu.models.sentiment import TRACKED_INDICES
    from svoc_tpu.models.tokenizer import load_tokenizer
    from svoc_tpu.train.trainer import Batch, init_state, make_sharded_train_step
    from svoc_tpu.utils.checkpoint import restore_train_state, save_train_state

    cfg = TINY_TEST
    tok = load_tokenizer(None, cfg.vocab_size, pad_id=cfg.pad_id, max_len=args.seq)
    rng = np.random.default_rng(0)
    eval_texts, eval_labels = make_dataset(
        rng, args.eval_n, TRACKED_INDICES, cfg.n_labels
    )
    eval_ids, eval_mask = tok(eval_texts, args.seq)

    def batches(seed):
        brng = np.random.default_rng(seed)
        while True:
            texts, labels = make_dataset(
                brng, args.batch, TRACKED_INDICES, cfg.n_labels
            )
            ids, mask = tok(texts, args.seq)
            yield Batch(ids=ids, mask=mask, labels=labels)

    model = SentimentEncoder(cfg)
    params = init_params(model, seed=0)
    tx = optax.adamw(args.lr)

    devices = np.array(jax.devices()[:8])

    def build(mesh_shape):
        mesh = Mesh(
            devices.reshape(mesh_shape), axis_names=("data", "model")
        )
        step_fn, shard_state, _ = make_sharded_train_step(
            model, tx, mesh, params_template=params, zero1=args.zero1
        )
        return mesh, step_fn, shard_state

    _, step_fn, shard_state = build((4, 2))

    def evaluate(p_tree) -> float:
        logits = model.apply(p_tree, eval_ids, eval_mask)
        pred = (np.asarray(jax.nn.sigmoid(logits)) > 0.5).astype(np.float32)
        idx = list(TRACKED_INDICES)
        return macro_f1(pred[:, idx], eval_labels[:, idx])

    state = shard_state(init_state(model, params, tx))
    f1_before = evaluate(state.params)

    half = args.steps // 2
    losses = []
    ckpt_dir = tempfile.mkdtemp(prefix="svoc_ft_")
    ckpt_path = os.path.join(ckpt_dir, "mid")
    gen = batches(seed=1)
    mid_state = None
    for i in range(args.steps):
        state, metrics = step_fn(state, next(gen))
        losses.append(float(metrics["loss"]))
        if i + 1 == half:
            save_train_state(ckpt_path, state)
            mid_state = state
    f1_after = evaluate(state.params)
    print(
        f"[finetune] loss {losses[0]:.3f} -> {losses[-1]:.3f}; "
        f"macro-F1 {f1_before:.3f} -> {f1_after:.3f}",
        flush=True,
    )

    # (a) same-mesh restore + replay => bit-identical final params.
    template = jax.tree_util.tree_map(np.asarray, mid_state)
    restored = shard_state(restore_train_state(ckpt_path, template))
    gen2 = batches(seed=1)
    for _ in range(half):
        next(gen2)  # skip the first half's batches
    for _ in range(half, args.steps):
        restored, _ = step_fn(restored, next(gen2))
    replay_delta = tree_max_abs_diff(restored.params, state.params)
    print(f"[finetune] same-mesh replay max|Δparams| = {replay_delta:.2e}",
          flush=True)

    # (b) changed-mesh restore: 4×2 → 2×4; values identical, training
    # continues.
    mesh_b, step_b, shard_b = build((2, 4))
    restored_b = shard_b(restore_train_state(ckpt_path, template))
    mesh_delta = tree_max_abs_diff(restored_b.params, mid_state.params)
    cont_losses = []
    gen3 = batches(seed=3)
    for _ in range(5):
        restored_b, m = step_b(restored_b, next(gen3))
        cont_losses.append(float(m["loss"]))
    print(
        f"[finetune] changed-mesh restore max|Δparams| = {mesh_delta:.2e}; "
        f"continued losses {['%.3f' % x for x in cont_losses]}",
        flush=True,
    )

    ok = (
        f1_after >= args.target_f1
        and replay_delta == 0.0
        and mesh_delta == 0.0
        and cont_losses[-1] < losses[half - 1] * 1.5
    )
    report = {
        "task": "synthetic keyword sentiment (6 tracked families)",
        "config": "TINY_TEST encoder, GSPMD data(4)xmodel(2) virtual mesh",
        "zero1_opt_sharding": bool(args.zero1),
        "steps": args.steps,
        "batch": args.batch,
        "loss_curve": [round(x, 4) for x in losses],
        "macro_f1_before": round(f1_before, 4),
        "macro_f1_after": round(f1_after, 4),
        "target_f1": args.target_f1,
        "same_mesh_replay_max_abs_param_delta": replay_delta,
        "changed_mesh_restore_max_abs_param_delta": mesh_delta,
        "changed_mesh_continued_losses": [round(x, 4) for x in cont_losses],
        "ok": bool(ok),
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"[finetune] wrote {args.out}; ok={ok}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
