#!/usr/bin/env python
"""Round-3 throughput experiments on the real chip (task: recover MFU).

Variants timed with the honest amortized protocol (dispatch N, fetch
last): batch size sweep, bf16-resident params, and a fleet/consensus
stage breakdown at 1024 oracles.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def amortized_ms(step, n=16):
    float(np.asarray(jnp.sum(step(0))))  # warm
    t0 = time.perf_counter()
    h = None
    for i in range(n):
        h = step(i + 1)
    float(np.asarray(jnp.sum(h)))
    return (time.perf_counter() - t0) / n * 1e3


def main():
    from svoc_tpu.consensus.kernel import ConsensusConfig, consensus_step
    from svoc_tpu.models.configs import ROBERTA_GO_EMOTIONS
    from svoc_tpu.models.sentiment import SentimentPipeline
    from svoc_tpu.sim.oracle import gen_oracle_predictions

    result = {}
    S = 128
    rng = np.random.default_rng(0)

    FLOPS_PER_TOK = 12 * (2 * (4 * 768 * 768 + 2 * 768 * 3072) + 4 * S * 768)

    for B in (256, 512, 1024):
        pipe = SentimentPipeline(
            cfg=ROBERTA_GO_EMOTIONS, seq_len=S, batch_size=B, tokenizer_name=None
        )
        fwd = pipe.forward_fn()
        pool = [
            jax.device_put(jnp.asarray(rng.integers(10, 5000, (B, S)), jnp.int32))
            for _ in range(4)
        ]
        mask = jax.device_put(jnp.ones((B, S), jnp.int32))

        ms = amortized_ms(lambda i: fwd(pipe.params, pool[i % 4], mask), n=12)
        mfu = B * S * FLOPS_PER_TOK / (ms / 1e3) / 197e12
        result[f"fwd_b{B}_f32params_ms"] = round(ms, 2)
        result[f"fwd_b{B}_f32params_mfu"] = round(mfu, 4)

        # bf16-resident params: one cast up front, matmuls read bf16
        bf16_params = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
            pipe.params,
        )
        ms = amortized_ms(lambda i: fwd(bf16_params, pool[i % 4], mask), n=12)
        mfu = B * S * FLOPS_PER_TOK / (ms / 1e3) / 197e12
        result[f"fwd_b{B}_bf16params_ms"] = round(ms, 2)
        result[f"fwd_b{B}_bf16params_mfu"] = round(mfu, 4)

    # fleet + consensus breakdown at 1024 oracles, window 50x6
    n_oracles = 1024
    ccfg = ConsensusConfig(n_failing=n_oracles // 8, constrained=True)
    window = jax.device_put(
        jnp.asarray(rng.uniform(0.01, 0.99, (50, 6)), jnp.float32)
    )
    key = jax.random.PRNGKey(0)

    fleet_only = jax.jit(
        lambda k: gen_oracle_predictions(k, window, n_oracles, ccfg.n_failing, 10)[0]
    )
    values0 = fleet_only(key)
    consensus_only = jax.jit(lambda v: consensus_step(v, ccfg).essence)

    result["fleet_only_ms"] = round(
        amortized_ms(lambda i: fleet_only(jax.random.fold_in(key, i)), n=16), 3
    )
    result["consensus_only_ms"] = round(
        amortized_ms(lambda i: consensus_only(values0 + 1e-6 * i), n=16), 3
    )

    fused = jax.jit(
        lambda k: consensus_step(
            gen_oracle_predictions(k, window, n_oracles, ccfg.n_failing, 10)[0], ccfg
        ).essence
    )
    result["fleet_consensus_fused_ms"] = round(
        amortized_ms(lambda i: fused(jax.random.fold_in(key, i)), n=16), 3
    )

    line = json.dumps(result)
    print(line, flush=True)
    with open("PERF_EXPERIMENTS.json", "w") as fh:
        fh.write(line + "\n")


if __name__ == "__main__":
    main()
