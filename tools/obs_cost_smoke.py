"""Cost-attribution plane smoke: fingerprint invisibility, gapless
timelines, universe-wide cost estimates, and JSONL reconstruction as a
CI gate (``make obs-cost-smoke``; docs/OBSERVABILITY.md
§cost-attribution).

The seeded serving scenario runs FOUR times — plane ON twice, plane
OFF twice (fresh journals, fresh metrics, a virtual clock) — and the
gate asserts:

1. **Fingerprint invisibility** — all four journal fingerprints are
   byte-identical: the plane's timelines, ledger samples, and obs
   records never touch the replay-pinned journal, so enabling cost
   attribution cannot change what a seeded replay reproduces.
2. **Gapless decomposition** — every completed request's stage
   durations telescope to its end-to-end latency (no unattributed
   time), and every stage the taxonomy names appears.
3. **Universe coverage** — ``CostModel.estimate`` returns a non-None
   warm AND cold figure for EVERY key the router's compile universe
   enumerates (exact cell, (N, M)-group fallback, or global pool), so
   the scheduler can price shapes it has never dispatched.
4. **Ledger reconstruction** — ``tools/obs_query.py --json`` refolds
   the streamed ``cost.sample`` records into EMAs identical to the
   live ledger's cells: the persisted ledger is recoverable from JSONL
   alone.
5. **Samples flowed** — the ON runs actually measured dispatches
   (nonzero ledger samples and observation records).

Usage::

    python tools/obs_cost_smoke.py [--seed 0] [--out OBS_COST_SMOKE.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform, so
# go through jax.config too — tools/soak.py measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import json  # noqa: E402
import subprocess  # noqa: E402
import sys  # noqa: E402
import tempfile  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

STAGES = (
    "queue_wait",
    "vectorize",
    "h2d",
    "dispatch",
    "sync",
    "commit",
    "respond",
)


def gapless(plane, tol=1e-6):
    """(checked, worst_gap, stage key set, positive-duration set) over
    completed timeline records: stage sums must telescope to e2e within
    ``tol``.  On the scenario's VIRTUAL clock intra-step stages are
    zero-width — only ``queue_wait`` (carried across steps) accrues
    time — so positivity is asserted for queue_wait alone while the
    full taxonomy is asserted by key presence."""
    checked, worst = 0, 0.0
    seen, positive = set(), set()
    for rec in plane.obslog.recent(10_000, kind="timeline.request"):
        data = rec.get("data") or {}
        if data.get("outcome") != "completed":
            continue
        stages = data.get("stages") or {}
        seen.update(stages)
        positive.update(k for k, v in stages.items() if v > 0.0)
        gap = abs(sum(stages.values()) - float(data.get("e2e_s", 0.0)))
        worst = max(worst, gap)
        checked += 1
    return checked, worst, seen, positive


def universe_coverage(result):
    """Every enumerated compile key must price (satellite of the
    scheduler handoff: estimates cover shapes never yet dispatched via
    the group/global fallbacks)."""
    from svoc_tpu.compile.universe import (
        enumerate_universe,
        registry_groups,
        universe_summary,
    )

    multi = result["multi"]
    router = multi.router
    keys = enumerate_universe(
        registry_groups(multi.registry),
        max_claims_per_batch=router.max_claims_per_batch,
        sanitized_dispatch=router.sanitized_dispatch,
        donate=router._donate,
        impl=router.consensus_impl,
        mesh=router.mesh_spec,
        mesh_claim_size=router._shard.claim_size if router._shard else 1,
    )
    model = result["cost_plane"].model
    uncovered = []
    sources = {}
    for key in keys:
        est = model.estimate(key)
        if est["warm"] is None or est["cold"] is None:
            uncovered.append(est["key"])
            continue
        for regime in ("warm", "cold"):
            src = est[regime]["source"]
            sources[src] = sources.get(src, 0) + 1
    return {
        "universe": universe_summary(keys),
        "estimated": len(keys) - len(uncovered),
        "uncovered": uncovered,
        "sources": sources,
    }


def reconstruction_identical(trace_path, plane):
    """Shell through ``obs_query --json`` and compare its refolded
    ledger against the live one, cell for cell (EMA determinism: same
    samples, same order, same alpha → identical floats)."""
    query = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "obs_query.py")
    proc = subprocess.run(
        [sys.executable, query, trace_path, "--tag",
         f"{trace_path}=smoke", "--json"],
        capture_output=True,
        text=True,
        check=False,
    )
    if proc.returncode != 0:
        return False, {"error": proc.stderr[-500:]}
    doc = json.loads(proc.stdout)
    rebuilt = doc["ledgers"]["smoke"]["ledger"]["entries"]
    live = plane.ledger.to_dict()["entries"]
    return rebuilt == live, {
        "rebuilt_keys": len(rebuilt),
        "live_keys": len(live),
        "samples": doc["ledgers"]["smoke"]["samples"],
        "timelines": len(doc["timelines"]),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="OBS_COST_SMOKE.json")
    args = p.parse_args(argv)

    from svoc_tpu.serving.scenario import run_serving_scenario

    with tempfile.TemporaryDirectory(prefix="obs_cost_smoke_") as tmp:
        trace_path = os.path.join(tmp, "obs_trace.jsonl")
        on_a = run_serving_scenario(
            args.seed, cost_plane="on", cost_trace_path=trace_path
        )
        on_b = run_serving_scenario(args.seed, cost_plane="on")
        off_a = run_serving_scenario(args.seed, cost_plane="off")
        off_b = run_serving_scenario(args.seed, cost_plane="off")

        fingerprints = [
            r["journal_fingerprint"] for r in (on_a, on_b, off_a, off_b)
        ]
        plane = on_a["cost_plane"]
        checked, worst_gap, stages_seen, stages_positive = gapless(plane)
        coverage = universe_coverage(on_a)
        ledger = plane.ledger.summary()
        rebuilt_ok, rebuild_info = reconstruction_identical(
            trace_path, plane
        )

    checks = {
        "fingerprints_identical": len(set(fingerprints)) == 1,
        "off_plane_inert": off_a["cost_plane"].snapshot()["ledger"][
            "samples"
        ] == 0,
        "timelines_gapless": checked > 0 and worst_gap <= 1e-6,
        "stages_observed": set(STAGES) <= stages_seen,
        "queue_wait_accrues": "queue_wait" in stages_positive,
        "universe_fully_estimated": not coverage["uncovered"],
        "ledger_samples_nonzero": ledger["samples"] > 0,
        "ledger_rebuilt_from_jsonl": rebuilt_ok,
    }
    ok = all(checks.values())
    artifact = {
        "seed": args.seed,
        "checks": checks,
        "ok": ok,
        "journal_fingerprint": fingerprints[0],
        "fingerprints": fingerprints,
        "timelines_checked": checked,
        "worst_gap_s": worst_gap,
        "stages_seen": sorted(stages_seen),
        "stages_positive": sorted(stages_positive),
        "coverage": coverage,
        "ledger": ledger,
        "reconstruction": rebuild_info,
        "completed": on_a["completed"],
        "shed": on_a["shed"],
    }
    atomic_write_json(args.out, artifact)
    for name, passed in checks.items():
        print(f"  {'PASS' if passed else 'FAIL'}  {name}")
    print(
        f"obs-cost-smoke {'OK' if ok else 'FAILED'}: 4x fingerprint "
        f"{fingerprints[0][:16]}, {checked} timelines gapless "
        f"(worst {worst_gap:.2e}s), {coverage['estimated']}/"
        f"{coverage['universe']['keys']} universe keys priced, "
        f"{ledger['samples']} samples over {ledger['keys']} keys, "
        f"JSONL rebuild {'identical' if rebuilt_ok else 'DIVERGED'} "
        f"-> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
