"""Flight-recorder smoke: journal replay identity + audit linkage +
bundle completeness as a CI gate.

Three legs (wired into ``make obs-smoke`` / ``presnapshot`` /
``verify``; seconds on CPU, no transformer builds):

1. **Journal replay identity** — the seeded Byzantine scenario
   (:func:`svoc_tpu.resilience.chaos.run_byzantine_scenario`) runs
   TWICE with fresh journals; the two event streams must digest
   byte-identically (``journal_fingerprint``), not just the outcomes.
2. **Audit linkage** — some one lineage id in the scenario's journal
   must link a refusing ``quarantine.verdict``, a
   ``supervisor.charge``, and a ``supervisor.replacement`` — the
   "which block got this oracle voted out" acceptance criterion.
3. **Bundle completeness + session lineage** — a seeded mini-session
   (synthetic store, fake vectorizer) runs fetch → commit; its journal
   must carry ``block.fetched`` / ``quarantine.verdict`` /
   ``consensus.result`` / ``commit.sent`` all on the block's lineage,
   the audit record must join events AND spans on that id, and a
   postmortem bundle built from the live singletons must carry every
   section (``BUNDLE_KEYS``) and read back as valid JSON.

Usage::

    python tools/obs_smoke.py [--seed 0] [--out OBS_SMOKE.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform, so
# go through jax.config too — tools/soak.py measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402
import tempfile  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402


def _audit_linkage(journal) -> dict:
    """Find a lineage linking verdict → charge → replacement."""
    by_lineage: dict = {}
    for e in journal.recent():
        if e.lineage is not None:
            by_lineage.setdefault(e.lineage, []).append(e)
    for lineage, events in by_lineage.items():
        has_verdict = any(
            e.type == "quarantine.verdict" and e.data.get("reasons")
            for e in events
        )
        charges = [e for e in events if e.type == "supervisor.charge"]
        replacements = [
            e for e in events if e.type == "supervisor.replacement"
        ]
        if has_verdict and charges and replacements:
            return {
                "lineage": lineage,
                "charged": sorted({str(c.data.get("oracle")) for c in charges}),
                "replaced": [
                    {"slot": r.data.get("slot"), "old": r.data.get("old")}
                    for r in replacements
                ],
            }
    return {}


def _session_leg(out_dir: str) -> dict:
    """Leg 3: seeded mini-session fetch+commit, audit + bundle."""
    import numpy as np

    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource
    from svoc_tpu.utils.events import journal
    from svoc_tpu.utils.postmortem import BUNDLE_KEYS, build_bundle

    def fake_vectorizer(texts):
        rng = np.random.default_rng(len(texts))
        v = rng.uniform(0.05, 0.95, size=(len(texts), 6))
        return v / v.sum(axis=1, keepdims=True)

    store = CommentStore()
    store.save(SyntheticSource(batch=120)())
    session = Session(
        config=SessionConfig(), store=store, vectorizer=fake_vectorizer
    )
    seq_before = journal.last_seq()
    session.fetch()
    outcome = session.commit_resilient()
    session.supervisor_step()
    slo = session.slo_snapshot()
    lineage = session.last_lineage

    block_events = {
        e.type for e in journal.recent(lineage=lineage) if e.seq > seq_before
    }
    needed = {
        "block.fetched",
        "quarantine.verdict",
        "consensus.result",
        "commit.sent",
    }
    audit = session.audit()
    bundle_path = build_bundle(
        out_dir=out_dir, trigger="obs_smoke", session=session
    )
    with open(bundle_path) as f:
        bundle = json.load(f)
    return {
        "lineage": lineage,
        "committed": outcome.sent,
        "commit_complete": bool(outcome.complete),
        "block_event_types": sorted(block_events),
        "missing_event_types": sorted(needed - block_events),
        "audit_found": bool(audit.get("found")),
        "audit_spans": len(audit.get("spans") or []),
        "audit_commit_sent": audit.get("summary", {}).get("commit_sent"),
        "slo_names": sorted(slo),
        "bundle_path": bundle_path,
        "bundle_missing_keys": sorted(
            k for k in BUNDLE_KEYS if k not in bundle
        ),
        "bundle_journal_events": len(bundle["journal"]["events"]),
    }


def _overhead_leg() -> dict:
    """A/B sanity: journal emission and lineage-tagged spans must stay
    in the PR-1 span cost class (microseconds — host-side, no device
    sync).  The bound is deliberately loose (1 ms/op mean) so a loaded
    CI box cannot flake it; the measured numbers land in the artifact
    for trend reading."""
    import time

    from svoc_tpu.utils.events import EventJournal
    from svoc_tpu.utils.metrics import MetricsRegistry, Tracer

    n = 5000
    reg = MetricsRegistry()
    j = EventJournal(reg, capacity=256)
    t0 = time.perf_counter()
    for i in range(n):
        j.emit("commit.sent", lineage="blk-000001", sent=7, total=7)
    emit_us = (time.perf_counter() - t0) / n * 1e6

    tracer = Tracer(reg)
    t0 = time.perf_counter()
    for i in range(n):
        with tracer.span("consensus"):
            pass
    span_us = (time.perf_counter() - t0) / n * 1e6
    t0 = time.perf_counter()
    for i in range(n):
        with tracer.span("consensus", lineage="blk-000001"):
            pass
    span_lineage_us = (time.perf_counter() - t0) / n * 1e6
    return {
        "emit_us_mean": round(emit_us, 3),
        "span_us_mean": round(span_us, 3),
        "span_lineage_us_mean": round(span_lineage_us, 3),
        "within_bounds": emit_us < 1000.0 and span_lineage_us < 1000.0,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="OBS_SMOKE.json")
    args = p.parse_args(argv)

    from svoc_tpu.resilience.chaos import run_byzantine_scenario
    from svoc_tpu.utils.events import EventJournal
    from svoc_tpu.utils.metrics import MetricsRegistry

    # Legs 1–2: the Byzantine scenario, twice, with fresh journals.
    j1 = EventJournal(MetricsRegistry())
    first = run_byzantine_scenario(args.seed, registry=MetricsRegistry(), journal=j1)
    j2 = EventJournal(MetricsRegistry())
    second = run_byzantine_scenario(args.seed, registry=MetricsRegistry(), journal=j2)
    linkage = _audit_linkage(j1)

    with tempfile.TemporaryDirectory() as tmp:
        session_leg = _session_leg(tmp)
    overhead = _overhead_leg()

    checks = {
        "journal_replay_identical": (
            first["journal_fingerprint"] == second["journal_fingerprint"]
        ),
        "scenario_replay_identical": (
            first["fingerprint"] == second["fingerprint"]
        ),
        "journal_nonempty": first["journal_events"] > 0,
        "audit_links_verdict_charge_replacement": bool(linkage),
        "session_block_events_complete": not session_leg["missing_event_types"],
        "session_audit_found": session_leg["audit_found"],
        "session_audit_has_spans": session_leg["audit_spans"] > 0,
        "session_commit_complete": session_leg["commit_complete"],
        "bundle_complete": not session_leg["bundle_missing_keys"],
        "slo_evaluated": len(session_leg["slo_names"]) == 3,
        "overhead_within_bounds": overhead["within_bounds"],
    }
    ok = all(checks.values())
    artifact = {
        "seed": args.seed,
        "checks": checks,
        "ok": ok,
        "journal_fingerprint": first["journal_fingerprint"],
        "journal_events": first["journal_events"],
        "audit_linkage": linkage,
        "session": session_leg,
        "overhead": overhead,
    }
    atomic_write_json(args.out, artifact)
    print(
        json.dumps(
            {
                "obs_smoke": "ok" if ok else "FAILED",
                "seed": args.seed,
                "checks": checks,
                "journal_events": first["journal_events"],
                "linkage": linkage,
                "journal_fingerprint": first["journal_fingerprint"][:16],
            }
        ),
        flush=True,
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
