#!/usr/bin/env python
"""Round-long hardware measurement campaign for a flapping TPU tunnel.

``tools/hw_queue.py`` assumes the tunnel stays up once it answers; on
2026-07-30 it was up for ~15 minutes, died mid-queue, and the alive
window went to the probes while every bench config fell back to CPU.
This script inverts the strategy:

- **liveness-gated**: a cheap fetch-proven matmul (90 s cap) runs
  before every item; while the tunnel is dead the campaign sleeps
  instead of burning item timeouts;
- **value-ordered**: bench configs first (flagship, packed,
  packed x flash, int8, DP serving), probes last — a short alive
  window captures the numbers that matter;
- **fallback-aware**: a bench line recorded on the CPU fallback
  (``rc == "cpu-fallback"`` from :func:`tools.hw_queue.run_item`)
  means the tunnel died mid-item; the attempt is refunded, the item
  stays pending, and the campaign goes back to watching — but
  fallbacks are counted per item (MAX_FALLBACKS) so a tunnel that
  passes liveness yet always fails bench's deeper backend probe
  retires the item instead of livelocking on it;
- **bounded retries**: a hard timeout or real failure (e.g. the
  consensus-kernel Mosaic compile hang seen in ``TPU_PROBE``) retires
  an item after MAX_ATTEMPTS so one wedged kernel cannot eat the
  round.

State after every step goes to ``HW_CAMPAIGN.json`` (atomic rename —
safe to poll); an in-progress item is flagged in
``/tmp/svoc_tpu_measuring`` so round automation can avoid competing
for the single host core while a timed measurement is live.  Run it in
the background for the whole round::

    python tools/hw_campaign.py [--seconds 10]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

from hw_queue import (  # noqa: E402
    BENCH_TIMEOUT_MARGIN_S,
    LIVENESS_SNIPPET,
    bench_cmd,
    run_item,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "HW_CAMPAIGN.json")
BUSY_FLAG = "/tmp/svoc_tpu_measuring"

MAX_ATTEMPTS = 3
# A liveness-passing tunnel whose bench still falls back to CPU (the
# 2026-07-30 morning pattern: 5 s matmul OK, bench's 120 s backend
# probe dead) must not livelock the head item: fallbacks are counted
# separately and retire the item at this cap.
MAX_FALLBACKS = 4
LIVENESS_TIMEOUT_S = 90.0
DEAD_SLEEP_S = 120.0


def bench_item(cfg: int, seconds: float):
    return {
        "name": f"bench_config{cfg}",
        "cmd": bench_cmd(cfg, seconds),
        "timeout": seconds + BENCH_TIMEOUT_MARGIN_S,
    }


def build_items(seconds: float):
    # Queue order = decision value per alive-minute (VERDICT r4 item 6:
    # the round-4 tunnel died after four items and the decision
    # measurements never ran).  Lossless trio first (they feed the
    # routing), then the two DECISION items (flash numerics parity, the
    # pallas-consensus config 6), then the routed flagship capture,
    # then int8 + DP serving, probes last.
    items = [bench_item(c, seconds) for c in (0, 8, 12)]
    items.append(
        # Flash on-HW parity with the dtype-aware bound (VERDICT r4
        # item 2) — adjudicates packed_flash's match_dense before the
        # routing that may pick it.
        {
            "name": "flash_parity",
            "cmd": ["tools/flash_probe.py", "--parity-only"],
            "timeout": 900,
        }
    )
    items.append(bench_item(6, seconds))  # pallas-consensus decision
    # Once the lossless variants are measured, tools/decide_perf.py
    # reroutes the flagship through PERF_DECISIONS.json; capture
    # config 0 again under the committed routing so the headline
    # number reflects the measured-best variant.  Distinct name so the
    # resume path keeps both the pre- and post-routing captures; the
    # campaign itself runs decide_perf.py right before this item (see
    # ``main``) so the routing can never be stale.
    routed = bench_item(0, seconds)
    routed["name"] = "bench_config0_routed"
    items.append(routed)
    items += [bench_item(c, seconds) for c in (10, 9, 11)]
    items += [
        # tpu_probe's consensus size-bisect doubles as the compile-hang
        # diagnosis; per-probe cap 300 s keeps one hang from eating the
        # whole item budget.  The outer cap exceeds the worst-case sum
        # of the inner probe caps (up to 9 runs x 300 s); the probe
        # also persists results incrementally, so even an outside kill
        # keeps what completed.
        {
            "name": "tpu_probe",
            "cmd": ["tools/tpu_probe.py", "--timeout", "300"],
            "timeout": 2800,
        },
        {"name": "flash_probe", "cmd": ["tools/flash_probe.py"], "timeout": 1500},
    ]
    for it in items:
        it.update(attempts=0, fallbacks=0, done=False, results=[])
    return items


def run_decide_perf(py: str):
    """Invoke tools/decide_perf.py and return ``(rc, flagship_variant)``
    — the routing freshness gate for the ``bench_config0_routed``
    capture (ADVICE r4: a stale PERF_DECISIONS.json made the routed
    item silently duplicate the pre-routing dense run)."""
    try:
        dec = subprocess.run(
            [py, "tools/decide_perf.py"],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )
        rc = dec.returncode
    except subprocess.TimeoutExpired:
        rc = "timeout"
    try:
        with open(os.path.join(REPO, "PERF_DECISIONS.json")) as f:
            variant = json.load(f).get("flagship_variant")
    except (OSError, ValueError, AttributeError):
        variant = None
    return rc, variant


def tunnel_alive(py: str) -> bool:
    try:
        proc = subprocess.run(
            [py, "-c", LIVENESS_SNIPPET],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=LIVENESS_TIMEOUT_S,
        )
        return proc.returncode == 0 and "LIVE" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def resume_items(items, prior_items):
    """Merge a prior journal's progress into a fresh item list.

    A campaign killed mid-round (session restart, OOM) must not re-run
    measurements it already captured: an alive window is the scarcest
    resource in the round.  Matching is by item name; captured
    results, attempt/fallback counters, and done flags carry over.
    Items added to ``build_items`` after the prior journal was written
    simply start fresh.

    Two resume invariants (ADVICE r4):

    - the journal flushes ``attempts += 1`` BEFORE ``run_item`` returns,
      so a kill mid-item leaves an attempt with no recorded result;
      every counted attempt/fallback appends exactly one result and the
      bounded trim (MAX_ATTEMPTS + MAX_FALLBACKS) can never drop one
      while the item is still pending, so the in-flight attempt is
      exactly ``attempts + fallbacks - len(results)`` — refund it
      rather than letting three restarts retire an item that never
      genuinely failed;
    - a DONE item's results were captured under the prior journal's
      cmd/timeout; carry those over so the journal keeps describing the
      command that actually produced the numbers even when the campaign
      is resumed with a different ``--seconds``.
    """
    prior = {
        it.get("name"): it
        for it in prior_items
        if isinstance(it, dict) and it.get("name")
    }
    for it in items:
        old = prior.get(it["name"])
        if not old:
            continue
        it["attempts"] = int(old.get("attempts", 0) or 0)
        it["fallbacks"] = int(old.get("fallbacks", 0) or 0)
        it["done"] = bool(old.get("done", False))
        it["results"] = list(old.get("results", []))
        if it["done"]:
            it["cmd"] = list(old.get("cmd", it["cmd"]))
            it["timeout"] = old.get("timeout", it["timeout"])
        else:
            in_flight = it["attempts"] + it["fallbacks"] - len(it["results"])
            if in_flight > 0:
                it["attempts"] = max(0, it["attempts"] - in_flight)
    return items


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seconds", type=float, default=10.0, help="bench window")
    p.add_argument(
        "--fresh",
        action="store_true",
        help="ignore an existing HW_CAMPAIGN.json instead of resuming it",
    )
    args = p.parse_args(argv)
    py = sys.executable

    items = build_items(args.seconds)
    started = time.strftime("%Y-%m-%d %H:%M:%S")
    liveness_checks = liveness_up = 0
    if not args.fresh:
        # Any malformed prior journal (including a JSON-valid non-dict
        # top level or null counters) starts fresh instead of crashing
        # the campaign (ADVICE r4).
        try:
            with open(OUT) as f:
                prior = json.load(f)
            if not isinstance(prior, dict):
                raise ValueError(f"journal top level is {type(prior).__name__}")
            items = resume_items(items, prior.get("items") or [])
            started = prior.get("started_at") or started
            liveness_checks = int(prior.get("liveness_checks") or 0)
            liveness_up = int(prior.get("liveness_up") or 0)
        except (OSError, ValueError, TypeError, AttributeError):
            pass
    state = {
        "started_at": started,
        "liveness_checks": liveness_checks,
        "liveness_up": liveness_up,
        "items": items,
    }

    def flush(note=""):
        state["updated_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
        atomic_write_json(OUT, state)
        if note:
            print(f"[campaign] {note}", flush=True)

    # A previous campaign killed mid-item (OOM, kill -9) may have left
    # the busy flag behind.  Check the pid it records: a LIVE pid means
    # another campaign is mid-measurement — refuse to start (two
    # campaigns would corrupt each other's numbers and flags); a dead
    # pid means the flag is stale — clear it.
    try:
        with open(BUSY_FLAG) as f:
            content = f.read()
    except OSError:
        content = None
    if content is not None:
        try:
            stale_pid = int(content.split()[0])
        except (ValueError, IndexError):
            # Corrupt/empty flag (writer killed mid-write): stale by
            # definition — clear it so automation stops deferring to a
            # phantom measurement.
            stale_pid = None
        alive = False
        if stale_pid is not None:
            try:
                os.kill(stale_pid, 0)
                alive = True
            except ProcessLookupError:
                alive = False
            except PermissionError:
                # EPERM = the process EXISTS (owned by another user):
                # that is a live campaign, not a stale flag.
                alive = True
            except OSError:
                alive = False
        if alive:
            print(
                f"[campaign] another campaign (pid {stale_pid}) is "
                "mid-measurement — refusing to start",
                flush=True,
            )
            return 2
        try:
            os.remove(BUSY_FLAG)
        except OSError:
            pass

    flush("started")
    while True:
        pending = [
            i
            for i in items
            if not i["done"]
            and i["attempts"] < MAX_ATTEMPTS
            and i["fallbacks"] < MAX_FALLBACKS
        ]
        if not pending:
            break
        state["liveness_checks"] += 1
        if not tunnel_alive(py):
            flush(f"tunnel dead ({len(pending)} pending) — sleeping")
            time.sleep(DEAD_SLEEP_S)
            continue
        state["liveness_up"] += 1
        item = pending[0]
        if item["name"] == "bench_config0_routed":
            # Derive the routing from the measurements just captured —
            # a missing/stale PERF_DECISIONS.json would make this item
            # silently duplicate the pre-routing dense run (ADVICE r4).
            dec_rc, variant = run_decide_perf(py)
            item["decide_perf_rc"] = dec_rc
            item["decided_variant"] = variant
            flush(f"decide_perf rc={dec_rc} -> flagship_variant={variant}")
        item["attempts"] += 1
        flush(f"tunnel up — running {item['name']} (attempt {item['attempts']})")
        try:
            with open(BUSY_FLAG, "w") as f:
                f.write(f"{os.getpid()} {item['name']}")
            res = run_item(item["name"], [py] + item["cmd"], item["timeout"])
        finally:
            try:
                os.remove(BUSY_FLAG)
            except OSError:
                pass
        item["results"].append(res)
        del item["results"][:-MAX_ATTEMPTS - MAX_FALLBACKS]  # bounded
        if res["rc"] == 0:
            item["done"] = True
            val = res.get("result", {}).get("value", "ok")
            flush(f"{item['name']}: DONE value={val} ({res['seconds']}s)")
        elif res["rc"] == "cpu-fallback":
            # Mid-item tunnel death, not an item failure: refund the
            # attempt (counted separately so a persistently half-dead
            # tunnel retires the item instead of livelocking on it),
            # and treat the tunnel as dead — sleep before re-probing.
            item["attempts"] -= 1
            item["fallbacks"] += 1
            flush(
                f"{item['name']}: cpu-fallback "
                f"({item['fallbacks']}/{MAX_FALLBACKS}) — sleeping"
            )
            time.sleep(DEAD_SLEEP_S)
        else:
            flush(f"{item['name']}: rc={res['rc']} ({res['seconds']}s)")

    done = sum(1 for i in items if i["done"])
    flush(f"campaign complete: {done}/{len(items)} items captured")
    return 0 if done == len(items) else 1


if __name__ == "__main__":
    sys.exit(main())
