#!/usr/bin/env python
"""Round-long hardware measurement campaign for a flapping TPU tunnel.

``tools/hw_queue.py`` assumes the tunnel stays up once it answers; on
2026-07-30 it was up for ~15 minutes, died mid-queue, and the alive
window went to the probes while every bench config fell back to CPU.
This script inverts the strategy:

- **liveness-gated**: a cheap fetch-proven matmul (90 s cap) runs
  before every item; while the tunnel is dead the campaign sleeps
  instead of burning item timeouts;
- **value-ordered**: bench configs first (flagship, packed,
  packed x flash, int8, DP serving), probes last — a short alive
  window captures the numbers that matter;
- **fallback-aware**: a bench line recorded on the CPU fallback
  (``rc == "cpu-fallback"`` from :func:`tools.hw_queue.run_item`)
  means the tunnel died mid-item; the attempt is refunded, the item
  stays pending, and the campaign goes back to watching — but
  fallbacks are counted per item (MAX_FALLBACKS) so a tunnel that
  passes liveness yet always fails bench's deeper backend probe
  retires the item instead of livelocking on it;
- **bounded retries**: a hard timeout or real failure (e.g. the
  consensus-kernel Mosaic compile hang seen in ``TPU_PROBE``) retires
  an item after MAX_ATTEMPTS so one wedged kernel cannot eat the
  round.

State after every step goes to ``HW_CAMPAIGN.json`` (atomic rename —
safe to poll); an in-progress item is flagged in
``/tmp/svoc_tpu_measuring`` so round automation can avoid competing
for the single host core while a timed measurement is live.  Run it in
the background for the whole round::

    python tools/hw_campaign.py [--seconds 10]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from hw_queue import (  # noqa: E402
    BENCH_TIMEOUT_MARGIN_S,
    LIVENESS_SNIPPET,
    bench_cmd,
    run_item,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "HW_CAMPAIGN.json")
BUSY_FLAG = "/tmp/svoc_tpu_measuring"

MAX_ATTEMPTS = 3
# A liveness-passing tunnel whose bench still falls back to CPU (the
# 2026-07-30 morning pattern: 5 s matmul OK, bench's 120 s backend
# probe dead) must not livelock the head item: fallbacks are counted
# separately and retire the item at this cap.
MAX_FALLBACKS = 4
LIVENESS_TIMEOUT_S = 90.0
DEAD_SLEEP_S = 120.0


def bench_item(cfg: int, seconds: float):
    return {
        "name": f"bench_config{cfg}",
        "cmd": bench_cmd(cfg, seconds),
        "timeout": seconds + BENCH_TIMEOUT_MARGIN_S,
    }


def build_items(seconds: float):
    items = [bench_item(c, seconds) for c in (0, 8, 12, 10, 9, 11, 6)]
    # Once the lossless variants are measured, tools/decide_perf.py
    # reroutes the flagship through PERF_DECISIONS.json; capture
    # config 0 again under the committed routing so the headline
    # number reflects the measured-best variant.  Distinct name so the
    # resume path keeps both the pre- and post-routing captures.
    routed = bench_item(0, seconds)
    routed["name"] = "bench_config0_routed"
    items.insert(4, routed)
    items += [
        # tpu_probe's consensus size-bisect doubles as the compile-hang
        # diagnosis; per-probe cap 300 s keeps one hang from eating the
        # whole item budget.  The outer cap exceeds the worst-case sum
        # of the inner probe caps (up to 9 runs x 300 s); the probe
        # also persists results incrementally, so even an outside kill
        # keeps what completed.
        {
            "name": "tpu_probe",
            "cmd": ["tools/tpu_probe.py", "--timeout", "300"],
            "timeout": 2800,
        },
        {"name": "flash_probe", "cmd": ["tools/flash_probe.py"], "timeout": 1500},
    ]
    for it in items:
        it.update(attempts=0, fallbacks=0, done=False, results=[])
    return items


def tunnel_alive(py: str) -> bool:
    try:
        proc = subprocess.run(
            [py, "-c", LIVENESS_SNIPPET],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=LIVENESS_TIMEOUT_S,
        )
        return proc.returncode == 0 and "LIVE" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def resume_items(items, prior_items):
    """Merge a prior journal's progress into a fresh item list.

    A campaign killed mid-round (session restart, OOM) must not re-run
    measurements it already captured: an alive window is the scarcest
    resource in the round.  Matching is by item name; captured
    results, attempt/fallback counters, and done flags carry over.
    Items added to ``build_items`` after the prior journal was written
    simply start fresh.
    """
    prior = {it.get("name"): it for it in prior_items if isinstance(it, dict)}
    for it in items:
        old = prior.get(it["name"])
        if not old:
            continue
        it["attempts"] = int(old.get("attempts", 0))
        it["fallbacks"] = int(old.get("fallbacks", 0))
        it["done"] = bool(old.get("done", False))
        it["results"] = list(old.get("results", []))
    return items


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seconds", type=float, default=10.0, help="bench window")
    p.add_argument(
        "--fresh",
        action="store_true",
        help="ignore an existing HW_CAMPAIGN.json instead of resuming it",
    )
    args = p.parse_args(argv)
    py = sys.executable

    items = build_items(args.seconds)
    started = time.strftime("%Y-%m-%d %H:%M:%S")
    liveness_checks = liveness_up = 0
    if not args.fresh:
        try:
            with open(OUT) as f:
                prior = json.load(f)
            items = resume_items(items, prior.get("items", []))
            started = prior.get("started_at", started)
            liveness_checks = int(prior.get("liveness_checks", 0))
            liveness_up = int(prior.get("liveness_up", 0))
        except (OSError, ValueError):
            pass
    state = {
        "started_at": started,
        "liveness_checks": liveness_checks,
        "liveness_up": liveness_up,
        "items": items,
    }

    def flush(note=""):
        state["updated_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
        tmp = OUT + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, OUT)
        if note:
            print(f"[campaign] {note}", flush=True)

    # A previous campaign killed mid-item (OOM, kill -9) may have left
    # the busy flag behind.  Check the pid it records: a LIVE pid means
    # another campaign is mid-measurement — refuse to start (two
    # campaigns would corrupt each other's numbers and flags); a dead
    # pid means the flag is stale — clear it.
    try:
        with open(BUSY_FLAG) as f:
            content = f.read()
    except OSError:
        content = None
    if content is not None:
        try:
            stale_pid = int(content.split()[0])
        except (ValueError, IndexError):
            # Corrupt/empty flag (writer killed mid-write): stale by
            # definition — clear it so automation stops deferring to a
            # phantom measurement.
            stale_pid = None
        alive = False
        if stale_pid is not None:
            try:
                os.kill(stale_pid, 0)
                alive = True
            except ProcessLookupError:
                alive = False
            except PermissionError:
                # EPERM = the process EXISTS (owned by another user):
                # that is a live campaign, not a stale flag.
                alive = True
            except OSError:
                alive = False
        if alive:
            print(
                f"[campaign] another campaign (pid {stale_pid}) is "
                "mid-measurement — refusing to start",
                flush=True,
            )
            return 2
        try:
            os.remove(BUSY_FLAG)
        except OSError:
            pass

    flush("started")
    while True:
        pending = [
            i
            for i in items
            if not i["done"]
            and i["attempts"] < MAX_ATTEMPTS
            and i["fallbacks"] < MAX_FALLBACKS
        ]
        if not pending:
            break
        state["liveness_checks"] += 1
        if not tunnel_alive(py):
            flush(f"tunnel dead ({len(pending)} pending) — sleeping")
            time.sleep(DEAD_SLEEP_S)
            continue
        state["liveness_up"] += 1
        item = pending[0]
        item["attempts"] += 1
        flush(f"tunnel up — running {item['name']} (attempt {item['attempts']})")
        try:
            with open(BUSY_FLAG, "w") as f:
                f.write(f"{os.getpid()} {item['name']}")
            res = run_item(item["name"], [py] + item["cmd"], item["timeout"])
        finally:
            try:
                os.remove(BUSY_FLAG)
            except OSError:
                pass
        item["results"].append(res)
        del item["results"][:-MAX_ATTEMPTS - MAX_FALLBACKS]  # bounded
        if res["rc"] == 0:
            item["done"] = True
            val = res.get("result", {}).get("value", "ok")
            flush(f"{item['name']}: DONE value={val} ({res['seconds']}s)")
        elif res["rc"] == "cpu-fallback":
            # Mid-item tunnel death, not an item failure: refund the
            # attempt (counted separately so a persistently half-dead
            # tunnel retires the item instead of livelocking on it),
            # and treat the tunnel as dead — sleep before re-probing.
            item["attempts"] -= 1
            item["fallbacks"] += 1
            flush(
                f"{item['name']}: cpu-fallback "
                f"({item['fallbacks']}/{MAX_FALLBACKS}) — sleeping"
            )
            time.sleep(DEAD_SLEEP_S)
        else:
            flush(f"{item['name']}: rc={res['rc']} ({res['seconds']}s)")

    done = sum(1 for i in items if i["done"])
    flush(f"campaign complete: {done}/{len(items)} items captured")
    return 0 if done == len(items) else 1


if __name__ == "__main__":
    sys.exit(main())
