"""Fleet observability plane smoke: replay invisibility, hop-join
coverage, merge-math identity, retired monotonicity, and the seeded
anomaly -> profile -> postmortem trigger chain as a CI gate
(``make fleet-obs-smoke``; docs/OBSERVABILITY.md §fleet-plane).

Three seeded cluster scenarios drive the gate:

1. **Kill/failover + migrate leg, plane ON twice / OFF twice** — all
   four ``fleet_fingerprint``s are byte-identical: hop records, merged
   telemetry, SLO alerts, and anomaly observations ride the obs
   channel only, so enabling the plane cannot change what a seeded
   fleet replay reproduces.  The OFF runs carry no plane state at all.
2. **Quiet leg (no kill)** — every counter family in the merged
   ``GET /metrics/fleet`` exposition equals the SUM of the per-source
   scrapes, series for series: the fleet view is arithmetic over the
   replica views, never a separate measurement.
3. **Degradation leg** — a mid-run replica kill under heavy arrivals
   produces a SUSTAINED seeded anomaly (EWMA z-score, thresholds
   pinned at construction), which auto-captures a profile and writes a
   postmortem bundle.  The kill leg also proves fleet totals never
   step backward across the failover (the ``@retired`` fold).

Usage::

    python tools/fleet_obs_smoke.py [--seed 3] [--out FLEET_OBS_SMOKE.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform, so
# go through jax.config too — tools/soak.py measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import re  # noqa: E402
import sys  # noqa: E402
import tempfile  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

KILL_PLAN = dict(
    n_replicas=3,
    n_claims=3,
    total_steps=8,
    arrivals_per_step=4,
    kill_replica="r1",
    kill_at_step=4,
    migrate_at_step=7,
)

#: Heavier traffic + a later kill: the outage window sheds enough per
#: step (delta >= min_delta, z >= threshold) for ``sustain_steps``
#: consecutive breaches — the smallest deterministic config that fires
#: the full anomaly -> profile -> bundle chain.
DEGRADATION_PLAN = dict(
    n_replicas=3,
    n_claims=6,
    total_steps=10,
    arrivals_per_step=12,
    kill_replica="r1",
    kill_at_step=5,
)


def hop_coverage(result):
    """Join every sidecar's hop records; coverage is total iff every
    chain classifies AND complete forward chains equal the router's
    ``cluster_forwarded`` count (no hop invisible to the join)."""
    from svoc_tpu.obsplane.hopchain import chain_stats, join_hop_chains
    from svoc_tpu.obsplane.timeline import read_observations

    records = []
    for path in result["fleet_obs"]["obs_paths"].values():
        records.extend(read_observations(path))
    chains = join_hop_chains(records)
    stats = chain_stats(chains)
    forwarded = sum(
        e["count"]
        for counters in result["fleet_obs"]["per_source_counters"].values()
        for e in counters
        if e["name"] == "cluster_forwarded"
    )
    complete_forwards = sum(
        1
        for c in chains.values()
        if c["reason"] == "forward" and c["classification"] == "complete"
    )
    classified = sum(stats["by_classification"].values())
    return {
        "stats": stats,
        "fully_classified": bool(chains) and classified == stats["chains"],
        "cluster_forwarded": forwarded,
        "complete_forwards": complete_forwards,
        "forwards_joined": complete_forwards == forwarded,
    }


_SERIES_RE = re.compile(r"^(svoc_\w+_total)(?:\{[^}]*\})? ([0-9.eE+-]+)$")


def exposition_totals(exposition):
    """Fold the Prometheus text back into ``{family_total: sum}``."""
    totals = {}
    for line in exposition.splitlines():
        m = _SERIES_RE.match(line)
        if m:
            totals[m.group(1)] = totals.get(m.group(1), 0.0) + float(
                m.group(2)
            )
    return totals


def merge_identity(result):
    """Merged exposition counter totals == sum over the per-source
    scrapes, family for family (quiet leg: nothing retired, so the
    per-source section is the whole fleet)."""
    merged = exposition_totals(result["fleet_obs"]["exposition"])
    scraped = {}
    for counters in result["fleet_obs"]["per_source_counters"].values():
        for e in counters:
            key = f"svoc_{e['name']}_total"
            scraped[key] = scraped.get(key, 0.0) + e["count"]
    mismatched = {
        k: {"merged": merged.get(k, 0.0), "scraped": scraped.get(k, 0.0)}
        for k in set(merged) | set(scraped)
        if abs(merged.get(k, 0.0) - scraped.get(k, 0.0)) > 1e-9
    }
    return {
        "families": len(merged),
        "mismatched": mismatched,
        "identical": bool(merged) and not mismatched,
    }


def monotonic(result):
    """No accounting family steps backward across the kill/failover
    (the ``@retired`` max-fold)."""
    from svoc_tpu.obsplane.fleet import ACCOUNTING_FAMILIES

    history = result["fleet_obs"]["accounting_history"]
    regressions = []
    for family in ACCOUNTING_FAMILIES:
        series = [h.get(family, 0.0) for h in history]
        for prev, cur in zip(series, series[1:]):
            if cur < prev:
                regressions.append({"family": family, "series": series})
                break
    return {"steps": len(history), "regressions": regressions}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=3)
    p.add_argument("--out", default="FLEET_OBS_SMOKE.json")
    args = p.parse_args(argv)

    from svoc_tpu.cluster.scenario import run_cluster_scenario

    with tempfile.TemporaryDirectory(prefix="fleet_obs_smoke_") as tmp:
        runs = {}
        for tag, plane in (
            ("on_a", True), ("on_b", True), ("off_a", False),
            ("off_b", False),
        ):
            runs[tag] = run_cluster_scenario(
                os.path.join(tmp, tag), args.seed, fleet_plane=plane,
                **KILL_PLAN,
            )
        quiet = run_cluster_scenario(
            os.path.join(tmp, "quiet"), args.seed, fleet_plane=True,
            n_replicas=3, n_claims=3, total_steps=6, arrivals_per_step=4,
        )
        degraded = run_cluster_scenario(
            os.path.join(tmp, "degraded"), args.seed, fleet_plane=True,
            **DEGRADATION_PLAN,
        )

        fingerprints = [runs[t]["fleet_fingerprint"] for t in sorted(runs)]
        coverage = hop_coverage(runs["on_a"])
        identity = merge_identity(quiet)
        mono = monotonic(runs["on_a"])
        snap = degraded["fleet_obs"]
        sustained = [a for a in snap["recent_anomalies"] if a["sustained"]]
        bundles = snap["bundles"]
        bundles_on_disk = [b for b in bundles if os.path.exists(b)]
        profiles = snap.get("profiler", {}).get("captures", 0)
        sidecars_present = all(
            os.path.exists(path)
            for path in runs["on_a"]["fleet_obs"]["obs_paths"].values()
        )

        checks = {
            "fleet_fingerprints_identical": len(set(fingerprints)) == 1,
            "off_plane_inert": all(
                runs[t]["fleet_obs"] == {"enabled": False}
                for t in ("off_a", "off_b")
            ),
            "sidecars_written": sidecars_present,
            "hop_chains_fully_classified": coverage["fully_classified"],
            "forwards_joined": coverage["forwards_joined"],
            "merged_equals_scrape_sum": identity["identical"],
            "totals_monotonic_across_failover": not mono["regressions"],
            "anomaly_sustained": len(sustained) >= 1,
            "profile_captured": profiles >= 1,
            "postmortem_bundle_written": len(bundles_on_disk) >= 1,
        }
        ok = all(checks.values())
        artifact = {
            "seed": args.seed,
            "checks": checks,
            "ok": ok,
            "fleet_fingerprint": fingerprints[0],
            "fingerprints": fingerprints,
            "hop_coverage": coverage,
            "merge_identity": {
                k: v for k, v in identity.items() if k != "mismatched"
            } | {"mismatched": list(identity["mismatched"])},
            "monotonicity": mono,
            "anomalies": snap["recent_anomalies"],
            "bundles": bundles,
            "profiler": snap.get("profiler"),
            "retired": snap["retired"],
            "observations": snap["observations"],
        }

    atomic_write_json(args.out, artifact)
    for name, passed in checks.items():
        print(f"  {'PASS' if passed else 'FAIL'}  {name}")
    stats = coverage["stats"]
    print(
        f"fleet-obs-smoke {'OK' if ok else 'FAILED'}: 4x fingerprint "
        f"{fingerprints[0][:16]}, {stats['chains']} hop chains "
        f"({coverage['complete_forwards']}/{coverage['cluster_forwarded']} "
        f"forwards joined), {identity['families']} merged families "
        f"identical to scrape sums, {len(sustained)} sustained "
        f"anomaly(ies) -> {profiles} profile(s) + {len(bundles)} "
        f"bundle(s) -> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
