"""Serving-tier smoke: seeded replay identity + graceful overload as a
CI gate (``make serving-smoke``; docs/SERVING.md §smoke).

The seeded virtual-time scenario
(:func:`svoc_tpu.serving.scenario.run_serving_scenario` — warm /
overload / recovery phases over 3 claims, a hot comment pool feeding
the dedup cache) runs TWICE with fresh journals, fresh metrics
registries, and a pinned lineage scope (the replay-pinning rules).
The gate asserts:

1. **Replay identity** — the journal fingerprint (every
   ``serving.admitted`` / ``serving.shed`` / ``serving.step`` /
   ``block.fetched`` / consensus / commit event, including every shed
   decision) digests byte-identically across the two runs, and so does
   every per-claim journal slice.
2. **Warm phase clean** — under-capacity arrivals shed ~nothing
   (admission control must not reject a healthy tier's traffic).
3. **Overload sheds** — the overload phase produces nonzero shed: the
   queue bounds + the ``request_latency`` burn threshold turn
   saturation into rejected requests, not an unbounded latency tail.
4. **Cache serves** — the hot pool produces real cache hits (the
   degrade-to-cached path works mid-overload).
5. **p99 reported** — the request-latency histogram saw completions
   and reports a finite p99.

Usage::

    python tools/serving_smoke.py [--seed 0] [--out SERVING_SMOKE.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform, so
# go through jax.config too — tools/soak.py measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="SERVING_SMOKE.json")
    args = p.parse_args(argv)

    from svoc_tpu.serving.scenario import run_serving_scenario

    first = run_serving_scenario(args.seed)
    second = run_serving_scenario(args.seed)

    warm, overload, recovery = first["phases"]
    per_claim_identical = {
        cid: (
            first["per_claim_fingerprints"][cid]
            == second["per_claim_fingerprints"][cid]
        )
        for cid in first["claims"]
    }
    latency = first["latency"]
    checks = {
        "journal_replay_identical": (
            first["journal_fingerprint"] == second["journal_fingerprint"]
        ),
        "per_claim_replay_identical": all(per_claim_identical.values()),
        "journal_nonempty": first["journal_events"] > 0,
        # ≤ 1% of warm arrivals shed (0 at the default seed; the slack
        # keeps alternate seeds honest rather than flaky).
        "warm_phase_clean": warm["shed"] <= 0.01 * warm["submitted"],
        "overload_sheds": overload["shed"] > 0,
        "cache_hits_nonzero": first["cache"]["hits"] > 0,
        "completions_nonzero": first["completed"] > 0,
        "p99_reported": (
            latency.get("count", 0) > 0
            and latency.get("p99") is not None
            and latency["p99"] < float("inf")
        ),
    }
    ok = all(checks.values())
    artifact = {
        "seed": args.seed,
        "checks": checks,
        "ok": ok,
        "per_claim_identical": per_claim_identical,
        "phases": first["phases"],
        "shed_by_reason": first["shed_by_reason"],
        "cache": first["cache"],
        "latency": latency,
        "submitted": first["submitted"],
        "admitted": first["admitted"],
        "cached": first["cached"],
        "shed": first["shed"],
        "completed": first["completed"],
        "journal_fingerprint": first["journal_fingerprint"],
        "journal_events": first["journal_events"],
    }
    atomic_write_json(args.out, artifact)
    for name, passed in checks.items():
        print(f"  {'PASS' if passed else 'FAIL'}  {name}")
    print(
        f"serving-smoke {'OK' if ok else 'FAILED'}: "
        f"{first['submitted']:g} arrivals over {first['steps']} steps "
        f"({len(first['claims'])} claims), shed {first['shed']:g} "
        f"(overload {overload['shed']:g}), cache hit rate "
        f"{first['cache']['hit_rate']:.1%}, p99 "
        f"{latency.get('p99', 0.0) * 1e3:.0f} ms -> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
