"""Offline cost-attribution queries over trace JSONL
(docs/OBSERVABILITY.md §cost-attribution).

Joins the three line shapes one or MANY svoc processes stream into
trace files — journal events (keyed ``"event"``), tracer spans (keyed
``"name"``), and observation records (keyed ``"obs"``) — into:

- **per-lineage timelines**: every ``timeline.request`` observation
  (stage decomposition + outcome) joined with that lineage's journal
  events and spans,
- **per-claim stage percentiles**: p50/p90/p99 seconds per stage per
  claim over completed requests,
- **cost-ledger reconstruction**: the ``cost.sample`` stream folded
  through the SAME order-deterministic EMA the live
  :class:`~svoc_tpu.obsplane.ledger.CostLedger` runs — identical
  samples in identical order reproduce the persisted cell values
  exactly, so a ledger is recoverable from JSONL alone (no snapshot
  needed).

Many files = many processes: each file is tagged with a source label
(``--tag path=name``; default the basename), and records are joined on
``(tag, lineage)`` unless ``--merge-scopes`` — two fleet processes
that happened to share a ``lineage_scope`` stay disambiguated per
file.

``--fleet`` switches to the fleet view (docs/OBSERVABILITY.md
§fleet-plane): every ``hop``-keyed observation record across the given
sidecars (the router's ``fleet-obs.jsonl`` + each replica's
``obs*.jsonl``) joins into cross-replica causal chains via
:func:`~svoc_tpu.obsplane.hopchain.join_hop_chains` — per-chain
timelines with send/recv/end sides, classification (``complete`` /
``terminal`` / ``died_mid_hop``), and a classification/reason summary.
A chain whose ``send`` has no answer is a mid-hop death: the origin's
sidecar is the only witness the request ever left.

Everything prints human-readable by default; ``--json`` emits one
machine-readable document (the smoke gate's round-trip check).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from svoc_tpu.obsplane.ledger import DEFAULT_ALPHA, CostLedger  # noqa: E402


def read_jsonl(path, keep=8):
    """All records from a (possibly rotated) trace file, oldest first,
    classified by line shape.  Torn tails (a crash mid-write) are
    skipped, matching ``read_trace_events``'s tolerance."""
    records = []
    paths = [f"{path}.{i}" for i in range(keep, 0, -1)] + [path]
    for p in paths:
        if not os.path.exists(p):
            continue
        with open(p, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if "obs" in rec:
                    rec["_shape"] = "obs"
                elif "event" in rec:
                    rec["_shape"] = "event"
                elif "name" in rec:
                    rec["_shape"] = "span"
                else:
                    continue
                records.append(rec)
    return records


def load_sources(paths, tags):
    """``[(tag, records)]`` per input file, tags unique."""
    out = []
    seen = set()
    for path in paths:
        tag = tags.get(path, os.path.basename(path))
        base, n = tag, 2
        while tag in seen:
            tag = f"{base}#{n}"
            n += 1
        seen.add(tag)
        out.append((tag, read_jsonl(path)))
    return out


def lineage_claim(lineage):
    """``blk<scope>-<claim>-rq<seq>`` → claim, else None (the plane's
    records carry the claim explicitly; this is the join fallback for
    bare journal events)."""
    if not lineage:
        return None
    parts = lineage.split("-")
    return parts[1] if len(parts) >= 3 else None


def build_timelines(sources, merge_scopes=False):
    """Per-lineage view: the ``timeline.request`` record + journal
    event types + span names joined on (tag, lineage)."""
    timelines = {}
    for tag, records in sources:
        for rec in records:
            lineage = rec.get("lineage")
            if not lineage:
                continue
            key = lineage if merge_scopes else f"{tag}:{lineage}"
            entry = timelines.setdefault(
                key,
                {
                    "lineage": lineage,
                    "source": tag,
                    "claim": lineage_claim(lineage),
                    "timeline": None,
                    "events": [],
                    "spans": [],
                },
            )
            shape = rec["_shape"]
            if shape == "obs" and rec.get("obs") == "timeline.request":
                data = rec.get("data") or {}
                entry["timeline"] = {
                    "outcome": data.get("outcome"),
                    "e2e_s": data.get("e2e_s"),
                    "stages": data.get("stages") or {},
                    **(
                        {"reason": data["reason"]}
                        if "reason" in data
                        else {}
                    ),
                }
                if data.get("claim"):
                    entry["claim"] = data["claim"]
            elif shape == "event":
                entry["events"].append(rec["event"])
            elif shape == "span":
                entry["spans"].append(rec["name"])
    return timelines


def percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def stage_percentiles(timelines):
    """p50/p90/p99 seconds per (claim, stage) over COMPLETED requests —
    shed/dropped outcomes carry partial stage sets and would skew the
    decomposition."""
    by_claim = {}
    for entry in timelines.values():
        tl = entry["timeline"]
        if tl is None or tl.get("outcome") != "completed":
            continue
        claim = entry["claim"] or "?"
        stages = by_claim.setdefault(claim, {})
        for stage, seconds in (tl.get("stages") or {}).items():
            stages.setdefault(stage, []).append(float(seconds))
    out = {}
    for claim, stages in sorted(by_claim.items()):
        out[claim] = {}
        for stage, vals in sorted(stages.items()):
            vals.sort()
            out[claim][stage] = {
                "n": len(vals),
                "p50": percentile(vals, 0.50),
                "p90": percentile(vals, 0.90),
                "p99": percentile(vals, 0.99),
            }
    return out


def reconstruct_ledger(sources, alpha=DEFAULT_ALPHA):
    """Fold every ``cost.sample`` record through the live ledger's EMA,
    in file order per source — the offline twin of the persisted
    ``cost_ledger.json``.  One ledger per source tag (different
    processes measured different hosts) plus sample counts."""
    ledgers = {}
    for tag, records in sources:
        ledger = CostLedger(alpha=alpha)
        n = 0
        for rec in records:
            if rec["_shape"] != "obs" or rec.get("obs") != "cost.sample":
                continue
            data = rec.get("data") or {}
            try:
                ledger.observe_key_str(
                    str(data["key"]),
                    str(data.get("group", "")),
                    str(data["warmth"]),
                    float(data["seconds"]),
                )
                n += 1
            except (KeyError, TypeError, ValueError):
                continue
        ledgers[tag] = {"samples": n, "ledger": ledger.to_dict()}
    return ledgers


def fleet_view(sources):
    """Join every ``hop`` observation across the sources into chains
    + summary stats (the ``--fleet`` document)."""
    from svoc_tpu.obsplane.hopchain import chain_stats, join_hop_chains

    hops = []
    other = {}
    for _tag, records in sources:
        for rec in records:
            if rec["_shape"] != "obs":
                continue
            if rec.get("obs") == "hop":
                hops.append(rec)
            else:
                kind = rec.get("obs")
                other[kind] = other.get(kind, 0) + 1
    chains = join_hop_chains(hops)
    return {
        "chains": {
            cid: {
                "claim": c["claim"],
                "lineage": c["lineage"],
                "reason": c["reason"],
                "src": c["src"],
                "dst": c["dst"],
                "classification": c["classification"],
                "outcome": c["outcome"],
                "attempts": c["attempts"],
                "dead_attempts": c["dead_attempts"],
                "records": [
                    {
                        "side": r["data"].get("side"),
                        "hop": r["data"].get("hop"),
                        **{
                            k: v
                            for k, v in r["data"].items()
                            if k
                            not in (
                                "side",
                                "hop",
                                "chain",
                                "claim",
                                "src",
                                "dst",
                                "reason",
                            )
                        },
                    }
                    for r in c["records"]
                ],
            }
            for cid, c in sorted(chains.items())
        },
        "stats": chain_stats(chains),
        "other_observations": other,
    }


def print_fleet(doc) -> None:
    stats = doc["stats"]
    print(
        f"{stats['chains']} hop chain(s), "
        f"{stats['dead_attempts']} dead attempt(s)"
    )
    for cls, n in sorted(stats["by_classification"].items()):
        print(f"  {cls:<14} {n}")
    print("by reason:")
    for reason, n in sorted(stats["by_reason"].items()):
        print(f"  {reason:<14} {n}")
    for cid, c in doc["chains"].items():
        if c["classification"] == "complete" and c["reason"] == "forward":
            continue  # routine; only the interesting chains narrate
        line = (
            f"{cid} {c['reason']} {c['src']}->{c['dst']} "
            f"claim={c['claim']} [{c['classification']}]"
        )
        if c["outcome"]:
            line += f" outcome={c['outcome']}"
        if c["dead_attempts"]:
            line += f" dead_attempts={c['dead_attempts']}"
        print(line)
        for r in c["records"]:
            extras = ", ".join(
                f"{k}={v}" for k, v in sorted(r.items()) if k not in ("side", "hop")
            )
            print(f"    hop {r['hop']} {r['side']}" + (f" ({extras})" if extras else ""))
    if doc["other_observations"]:
        print("other observations:")
        for kind, n in sorted(doc["other_observations"].items()):
            print(f"  {kind:<20} {n}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("files", nargs="+", help="trace JSONL file(s)")
    parser.add_argument(
        "--fleet",
        action="store_true",
        help="join hop chains across the given observation sidecars "
        "(docs/OBSERVABILITY.md §fleet-plane)",
    )
    parser.add_argument(
        "--tag",
        action="append",
        default=[],
        metavar="PATH=NAME",
        help="source label for a file (default: basename)",
    )
    parser.add_argument(
        "--merge-scopes",
        action="store_true",
        help="join lineages across files (default: per-file keys)",
    )
    parser.add_argument("--lineage", help="show one lineage only")
    parser.add_argument("--claim", help="filter timelines to one claim")
    parser.add_argument(
        "--alpha",
        type=float,
        default=DEFAULT_ALPHA,
        help="EMA alpha for ledger reconstruction (default: %(default)s)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    args = parser.parse_args(argv)

    tags = {}
    for spec in args.tag:
        if "=" not in spec:
            parser.error(f"--tag wants PATH=NAME, got {spec!r}")
        path, name = spec.split("=", 1)
        tags[path] = name

    sources = load_sources(args.files, tags)
    if args.fleet:
        doc = fleet_view(sources)
        if args.as_json:
            print(json.dumps(doc, indent=1, sort_keys=True))
        else:
            for tag, records in sources:
                print(f"source {tag}: {len(records)} records")
            print_fleet(doc)
        return 0
    timelines = build_timelines(sources, merge_scopes=args.merge_scopes)
    if args.lineage:
        timelines = {
            k: v
            for k, v in timelines.items()
            if v["lineage"] == args.lineage
        }
    if args.claim:
        timelines = {
            k: v for k, v in timelines.items() if v["claim"] == args.claim
        }
    percentiles = stage_percentiles(timelines)
    ledgers = reconstruct_ledger(sources, alpha=args.alpha)

    doc = {
        "sources": {
            tag: {"records": len(records)} for tag, records in sources
        },
        "timelines": {
            k: {kk: vv for kk, vv in v.items()}
            for k, v in sorted(timelines.items())
        },
        "stage_percentiles": percentiles,
        "ledgers": ledgers,
    }
    if args.as_json:
        print(json.dumps(doc, indent=1, sort_keys=True))
        return 0

    for tag, records in sources:
        print(f"source {tag}: {len(records)} records")
    with_tl = [v for v in timelines.values() if v["timeline"] is not None]
    print(
        f"{len(timelines)} lineages, {len(with_tl)} with timelines "
        f"({sum(1 for v in with_tl if v['timeline']['outcome'] == 'completed')}"
        " completed)"
    )
    if args.lineage:
        for entry in with_tl:
            tl = entry["timeline"]
            print(f"  {entry['lineage']} [{entry['source']}] "
                  f"claim={entry['claim']} outcome={tl['outcome']} "
                  f"e2e={tl['e2e_s']:.4f}s")
            for stage, seconds in tl["stages"].items():
                print(f"    {stage:<12} {seconds:.4f}s")
            print(f"    events: {', '.join(entry['events']) or '(none)'}")
    for claim, stages in percentiles.items():
        print(f"claim {claim}:")
        for stage, p in stages.items():
            print(
                f"  {stage:<12} n={p['n']:<5} p50={p['p50']:.4f}s "
                f"p90={p['p90']:.4f}s p99={p['p99']:.4f}s"
            )
    for tag, rec in ledgers.items():
        entries = rec["ledger"]["entries"]
        print(
            f"ledger [{tag}]: {rec['samples']} samples, "
            f"{len(entries)} keys (alpha={rec['ledger']['alpha']})"
        )
        for key_str, entry in sorted(entries.items()):
            cells = "  ".join(
                f"{w}: {c['ema_s'] * 1e3:.2f} ms ({c['samples']}x)"
                for w, c in sorted(entry["warmth"].items())
            )
            print(f"  {key_str} [{entry['group']}]  {cells}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
