"""Cluster scaling bench: aggregate QPS vs replica count (ISSUE 18).

Runs the seeded fleet scenario (:func:`svoc_tpu.cluster.scenario
.run_cluster_scenario`) at FIXED total work — same claims, same
arrival schedule, same steps — for 1, 2, and 4 replicas, and measures
aggregate completed-requests-per-wall-second.  No kill, no injected
faults: this is the routing question ("do more replicas add serving
throughput here?"), not the robustness gate (``make cluster-smoke``).

Honesty protocol (the ``BENCH_SHARD_r07.json`` precedent): on this
1-physical-core container the replicas time-slice the same core, so
fixed-total-work scaling is bounded at ~1.0x by construction and the
artifact records ``scaling_verdict: "null"`` with the blocker spelled
out — the routed default (``cluster_replicas: "1"``, see
``tools/decide_perf.py``) must stand until real multi-core/TPU hosts
measure a win.  Every item stamps ``device_topology`` so a reader can
tell a 1-core simulation from real hardware at a glance.

Usage::

    python tools/bench_cluster.py [--seed 0] [--steps 8] [--out BENCH_CLUSTER_r11.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import sys  # noqa: E402
import tempfile  # noqa: E402
import time  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import device_topology  # noqa: E402
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

REPLICA_COUNTS = (1, 2, 4)
N_CLAIMS = 4
ARRIVALS_PER_STEP = 8


def bench_point(n_replicas: int, seed: int, steps: int) -> dict:
    from svoc_tpu.cluster.scenario import run_cluster_scenario

    # Two runs per point, keep the second: the first run pays the JAX
    # compile cost for this point's claims-per-replica batch shapes,
    # which would otherwise swamp the (short) serving measurement and
    # fabricate a "scaling win" that is really compile amortisation.
    for attempt in range(2):
        workdir = tempfile.mkdtemp(prefix=f"bench-cluster-{n_replicas}r-")
        t0 = time.perf_counter()
        result = run_cluster_scenario(
            workdir,
            seed=seed,
            n_replicas=n_replicas,
            n_claims=N_CLAIMS,
            total_steps=steps,
            arrivals_per_step=ARRIVALS_PER_STEP,
            stale_epoch_probe=False,
        )
        elapsed = time.perf_counter() - t0
    requests = result["requests"]
    completed = float(requests["completed"])
    return {
        "metric": (
            f"cluster aggregate serving {N_CLAIMS} claims x "
            f"{ARRIVALS_PER_STEP}/step @ {n_replicas} replica(s)"
        ),
        "value": round(completed / elapsed, 2) if elapsed > 0 else 0.0,
        "unit": "completed_requests/sec",
        "rc": 0,
        "detail": {
            "n_replicas": n_replicas,
            "n_claims": N_CLAIMS,
            "total_steps": steps,
            "arrivals_per_step": ARRIVALS_PER_STEP,
            "wall_s": round(elapsed, 3),
            "completed": completed,
            "admitted": float(requests["admitted"]),
            "dropped": float(requests["dropped"]),
            "unaccounted": float(requests["unaccounted"]),
            "duplicate_txs": result["duplicate_txs"],
            "epoch": result["epoch"],
            "fleet_fingerprint": result["fleet_fingerprint"],
            "device_topology": device_topology(),
        },
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--out", default="BENCH_CLUSTER_r11.json")
    args = parser.parse_args()

    items = []
    for n in REPLICA_COUNTS:
        item = bench_point(n, args.seed, args.steps)
        print(
            f"[bench_cluster] {n} replica(s): {item['value']} "
            f"{item['unit']} (wall {item['detail']['wall_s']}s)"
        )
        items.append(item)

    base = items[0]["value"] or 1.0
    scaling = {
        str(it["detail"]["n_replicas"]): round(it["value"] / base, 3)
        for it in items
    }
    topology = items[0]["detail"]["device_topology"]
    host_cores = topology.get("host_cpu_count") or 1
    # The verdict rule mirrors the shard sweep: a ≥1.5x aggregate-QPS
    # win at 1→4 replicas with clean fleet invariants is "scales";
    # a 1-core host cannot produce that by construction and records
    # the honest null instead of implying a routing defect.
    clean = all(
        it["detail"]["duplicate_txs"] == 0
        and it["detail"]["unaccounted"] == 0.0
        for it in items
    )
    scaling_1_to_4 = scaling.get("4", 0.0)
    if host_cores <= 1:
        verdict = "null"
        blocker = (
            f"host exposes {host_cores} physical core(s); every replica "
            "is a thread time-slicing the same core, so fixed-total-work "
            "aggregate QPS is bounded at <= ~1.0x here — replica-count "
            "routing needs real multi-core/TPU hosts (the "
            "BENCH_SHARD_r07 precedent)"
        )
    elif clean and scaling_1_to_4 >= 1.5:
        verdict = "scales"
        blocker = None
    else:
        verdict = "null"
        blocker = (
            f"1->4 replica scaling {scaling_1_to_4}x < 1.5x threshold"
            if clean
            else "fleet invariants not clean (duplicate/unaccounted != 0)"
        )

    artifact = {
        "artifact": "BENCH_CLUSTER_r11",
        "date": time.strftime("%Y-%m-%d"),
        "platform": topology.get("platform", "cpu"),
        "fixed_total_work": {
            "n_claims": N_CLAIMS,
            "total_steps": args.steps,
            "arrivals_per_step": ARRIVALS_PER_STEP,
        },
        "seed": args.seed,
        "scaling_vs_1_replica": scaling,
        "scaling_1_to_4_replicas": scaling_1_to_4,
        "fleet_invariants_clean": clean,
        "scaling_verdict": verdict,
        "scaling_blocker": blocker,
        "items": items,
    }
    atomic_write_json(args.out, artifact)
    print(
        f"[bench_cluster] verdict={verdict} scaling={scaling} -> {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
