"""Cold-start smoke: prewarm → kill → restart → warm, fingerprint-gated
(``make coldstart-smoke``; docs/PARALLELISM.md §compile-plane).

Three child processes run the SAME seeded 4-claim fabric scenario:

1. ``control``      — no compilation cache, no warmup: the historical
                      compile-on-first-dispatch behavior.
2. ``warm_first``   — a persistent compilation cache under a durable
                      dir + a synchronous AOT prewarm before the first
                      cycle.  This child POPULATES the cache and is
                      then SIGKILLed (it parks after reporting) — the
                      PR 8 kill, applied to the compile plane.
3. ``warm_restart`` — a fresh process on the SAME cache dir, prewarm
                      again, run the scenario.

The gate asserts:

- **Warmup is invisible to replays** — per-claim and whole-journal
  fingerprints of all three runs are byte-identical (warmup never
  journals, never changes numerics; the fingerprint-compatibility
  discipline of PR 13 applied to the compile plane).
- **0 fresh compiles after the restart** — the ``warm_restart`` child
  ends with ZERO persistent-cache misses: every program it ran (the
  prewarmed claim cubes AND every auxiliary jit the scenario touches)
  was served from the cache the killed process left behind.
- **The witness is not vacuous** — the ``warm_first`` child recorded
  nonzero cache misses (it really did populate the cache) and the
  restart's prewarm walk visibly finished its universe.

Usage::

    python tools/coldstart_smoke.py [--seed 0] [--out COLDSTART_SMOKE.json]
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import json  # noqa: E402
import select  # noqa: E402
import signal  # noqa: E402
import subprocess  # noqa: E402
import sys  # noqa: E402
import tempfile  # noqa: E402
import time  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402


def child(leg: str, seed: int, cycles: int, cache_dir: str) -> None:
    """One scenario leg; prints a single JSON line, then (warm_first)
    parks for the parent's SIGKILL."""
    from svoc_tpu.utils.metrics import install_compile_listener, registry

    install_compile_listener()
    if leg != "control":
        from svoc_tpu.compile.cache import enable_persistent_cache

        enabled = enable_persistent_cache(cache_dir)
        assert enabled, "persistent cache must enable for warm legs"

    from svoc_tpu.fabric.scenario import run_fabric_scenario

    result = run_fabric_scenario(
        seed, cycles=cycles, warmup=(leg != "control")
    )

    def cache_events(event: str) -> float:
        return registry.counter(
            "xla_cache_events", labels={"event": event}
        ).count

    print(
        json.dumps(
            {
                "leg": leg,
                "journal_fingerprint": result["journal_fingerprint"],
                "claims": {
                    c: result["claims"][c]["fingerprint"]
                    for c in sorted(result["claims"])
                },
                "cache_misses": cache_events("miss"),
                "cache_hits": cache_events("hit"),
            }
        ),
        flush=True,
    )
    if leg == "warm_first":
        # Park: the parent SIGKILLs this process — compiled programs
        # must survive an unclean death (they are written at compile
        # time, not at exit), exactly like WAL records survive one.
        signal.pause()


def run_leg(
    leg: str, seed: int, cycles: int, cache_dir: str, kill: bool = False
) -> dict:
    cmd = [
        sys.executable,
        os.path.abspath(__file__),
        "--child",
        leg,
        "--seed",
        str(seed),
        "--cycles",
        str(cycles),
        "--cache-dir",
        cache_dir,
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # stderr goes to a FILE, not a pipe: a chatty child (per-shape jax
    # warnings across the whole universe) filling a 64 KB stderr pipe
    # would deadlock against our blocking stdout read — the
    # crash_smoke.py lesson, solved here without communicate() because
    # the warm_first child must stay ALIVE for the parent's SIGKILL.
    with tempfile.TemporaryFile(mode="w+") as err:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=err, text=True,
            env=env, cwd=REPO,
        )
        try:
            ready, _w, _x = select.select([proc.stdout], [], [], 600)
            line = proc.stdout.readline() if ready else ""
            if not line:
                proc.kill()
                proc.wait(timeout=10)
                err.seek(0)
                raise RuntimeError(
                    f"leg {leg} died before reporting: "
                    f"{err.read()[-2000:]}"
                )
            if kill:
                proc.kill()  # SIGKILL mid-life: the compile plane's crash
            proc.wait(timeout=600)
            return json.loads(line)
        finally:
            if proc.poll() is None:
                proc.kill()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--cycles", type=int, default=8)
    p.add_argument("--out", default="COLDSTART_SMOKE.json")
    p.add_argument("--child", default=None)
    p.add_argument("--cache-dir", default=None)
    args = p.parse_args(argv)

    if args.child:
        child(args.child, args.seed, args.cycles, args.cache_dir)
        return 0

    t0 = time.time()
    with tempfile.TemporaryDirectory(prefix="svoc-coldstart-smoke-") as tmp:
        cache_dir = os.path.join(tmp, "durable")
        control = run_leg("control", args.seed, args.cycles, cache_dir)
        warm_first = run_leg(
            "warm_first", args.seed, args.cycles, cache_dir, kill=True
        )
        warm_restart = run_leg(
            "warm_restart", args.seed, args.cycles, cache_dir
        )

    claim_ids = sorted(control["claims"])
    checks = {
        "warmed_equals_control": (
            warm_first["claims"] == control["claims"]
            and warm_first["journal_fingerprint"]
            == control["journal_fingerprint"]
        ),
        "restart_equals_control": (
            warm_restart["claims"] == control["claims"]
            and warm_restart["journal_fingerprint"]
            == control["journal_fingerprint"]
        ),
        "first_run_populated_cache": warm_first["cache_misses"] > 0,
        "zero_fresh_compiles_after_restart": (
            warm_restart["cache_misses"] == 0
        ),
        "restart_really_hit_cache": warm_restart["cache_hits"] > 0,
    }
    ok = all(checks.values())
    artifact = {
        "seed": args.seed,
        "cycles": args.cycles,
        "elapsed_s": round(time.time() - t0, 1),
        "checks": checks,
        "ok": ok,
        "legs": {
            "control": control,
            "warm_first": warm_first,
            "warm_restart": warm_restart,
        },
    }
    atomic_write_json(args.out, artifact)
    for name, passed in checks.items():
        print(f"  {'PASS' if passed else 'FAIL'}  {name}")
    print(
        f"coldstart-smoke {'OK' if ok else 'FAILED'}: {len(claim_ids)} "
        f"claims × {args.cycles} cycles — prewarmed + SIGKILLed + "
        f"restarted warm ({int(warm_restart['cache_hits'])} cache hits, "
        f"{int(warm_restart['cache_misses'])} misses), fingerprints "
        f"identical to the unwarmed control -> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
