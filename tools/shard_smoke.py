"""Sharded claim-cube smoke: mesh-pinned replay identity as a CI gate
(``make shard-smoke``; docs/PARALLELISM.md §sharded-claims).

The seeded fabric scenario (4 claims × 8 oracles — 8 so the 2×4 mesh's
oracle axis divides the fleet) runs THREE times on the 8-device
simulated CPU mesh:

1. twice MESH-PINNED (``mesh="2x4"``) with fresh journals/registries
   and the pinned lineage scope — byte-identical per-claim journal
   fingerprints, the replay witness covering scheduling AND the
   sharded dispatch;
2. once UNMESHED — and its per-claim fingerprints must equal the
   meshed ones byte-for-byte: the sharded dispatch path is
   bitwise-exact vs the single-device cube
   (``parallel/claim_shard.py`` exact-parity contract), so pinning a
   mesh may never change what the fabric journals.

The gate also asserts the mesh actually served (nonzero
``claim_shard_dispatches``, zero ``claim_shard_fallback`` — a silently
falling-back mesh would pass the fingerprint checks vacuously) and
that the scenario's Byzantine accounting (offender replaced, siblings
clean) survives the sharded path.

Usage::

    python tools/shard_smoke.py [--seed 0] [--out SHARD_SMOKE.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction, with the 8-device simulated mesh pinned
# BEFORE the first jax import (the mesh needs the device count; the
# axon sitecustomize pins the platform, so go through jax.config too —
# tools/fabric_smoke.py discipline).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import argparse  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

MESH = "2x4"
N_ORACLES = 8  # divisible by the mesh oracle axis


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--cycles", type=int, default=10)
    p.add_argument("--out", default="SHARD_SMOKE.json")
    args = p.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")

    from svoc_tpu.fabric.scenario import run_fabric_scenario
    from svoc_tpu.utils.metrics import MetricsRegistry

    def meshed_run():
        metrics = MetricsRegistry()
        result = run_fabric_scenario(
            args.seed,
            cycles=args.cycles,
            n_oracles=N_ORACLES,
            mesh=MESH,
            metrics=metrics,
        )
        result["shard_dispatches"] = metrics.family_total(
            "claim_shard_dispatches"
        )
        result["shard_fallbacks"] = metrics.family_total(
            "claim_shard_fallback"
        )
        return result

    first = meshed_run()
    second = meshed_run()
    # mesh="off", not None: None would re-resolve SVOC_MESH / the
    # committed claim_mesh record, and a pinned environment would turn
    # the control run sharded too — the meshed==unmeshed witness must
    # compare against the EXPLICITLY unsharded path.
    unmeshed = run_fabric_scenario(
        args.seed, cycles=args.cycles, n_oracles=N_ORACLES, mesh="off"
    )

    claim_ids = sorted(first["claims"])
    meshed_identical = {
        cid: (
            first["claims"][cid]["fingerprint"]
            == second["claims"][cid]["fingerprint"]
        )
        for cid in claim_ids
    }
    mesh_vs_single = {
        cid: (
            first["claims"][cid]["fingerprint"]
            == unmeshed["claims"][cid]["fingerprint"]
        )
        for cid in claim_ids
    }
    checks = {
        "meshed_replay_identical": all(meshed_identical.values()),
        "meshed_journal_identical": (
            first["journal_fingerprint"] == second["journal_fingerprint"]
        ),
        # The exact-parity contract made observable: a pinned mesh
        # changes WHERE the cube computes, never what it computes.
        "meshed_equals_unmeshed": all(mesh_vs_single.values())
        and first["journal_fingerprint"] == unmeshed["journal_fingerprint"],
        "journal_nonempty": first["journal_events"] > 0,
        # The mesh really served: a cube the mesh could not shard would
        # pass the fingerprint checks through the (also-exact) fallback
        # path — the gate requires zero fallbacks and a dispatch per
        # fabric cycle.
        "sharded_dispatches_happened": first["shard_dispatches"]
        >= args.cycles,
        "zero_shard_fallbacks": first["shard_fallbacks"] == 0,
        "injections_happened": first["injection_count"] > 0,
        "offender_replaced": first["offender_replaced"],
        "siblings_clean": first["siblings_clean"],
    }
    report = {
        "seed": args.seed,
        "cycles": args.cycles,
        "mesh": MESH,
        "n_oracles": N_ORACLES,
        "checks": checks,
        "per_claim_meshed_identical": meshed_identical,
        "per_claim_mesh_vs_single": mesh_vs_single,
        "shard_dispatches": first["shard_dispatches"],
        "shard_fallbacks": first["shard_fallbacks"],
        "injection_count": first["injection_count"],
        "journal_fingerprint": first["journal_fingerprint"],
        "ok": all(checks.values()),
    }
    atomic_write_json(args.out, report)
    for name, passed in checks.items():
        print(f"[shard-smoke] {'PASS' if passed else 'FAIL'} {name}")
    print(
        f"[shard-smoke] {'OK' if report['ok'] else 'FAILED'} — "
        f"mesh {MESH}, {first['shard_dispatches']:.0f} sharded "
        f"dispatches, fingerprints {'stable' if report['ok'] else 'UNSTABLE'}"
        f" ({args.out})"
    )
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
