"""Persistent tunnel watcher: loop until TPU liveness, then run hw_queue.

**Superseded by ``tools/hw_campaign.py``** — the 2026-07-30 alive
window showed the one-shot fire-the-queue strategy loses the window to
probes when the tunnel dies mid-queue; the campaign re-gates liveness
per item, orders by value, and survives flapping.  This wrapper is
kept for the simple case (a tunnel that stays up once it answers).

``tools/hw_queue.py`` aborts early (by design) when the tunnel is dead so
its artifact records the outage.  This wrapper is the long-running side:
probe liveness every ``--interval`` seconds and, the moment a probe
passes, run the full queue once and exit with its code.  Intended to run
in a tmux/background session for the whole round.

Usage::

    python tools/hw_watch.py [--interval 600] [--seconds 10]
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LIVENESS_SNIPPET = (
    "import jax, jax.numpy as jnp, numpy as np;"
    "assert jax.devices()[0].platform == 'tpu', jax.devices();"
    "x = jnp.ones((1024, 1024), jnp.bfloat16);"
    "s = float(np.asarray(jnp.sum(jax.jit(lambda a: a @ a)(x))));"
    "print('LIVE', s)"
)


def probe(timeout_s: float = 240.0) -> bool:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", LIVENESS_SNIPPET],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--interval", type=float, default=600.0)
    p.add_argument("--seconds", type=float, default=10.0)
    args = p.parse_args(argv)

    attempt = 0
    while True:
        attempt += 1
        stamp = time.strftime("%H:%M:%S")
        print(f"[hw_watch] probe #{attempt} at {stamp} ...", flush=True)
        if probe():
            print("[hw_watch] TPU LIVE — running hw_queue", flush=True)
            rc = subprocess.run(
                [
                    sys.executable,
                    "tools/hw_queue.py",
                    "--seconds",
                    str(args.seconds),
                ],
                cwd=REPO,
            ).returncode
            print(f"[hw_watch] hw_queue rc={rc}", flush=True)
            return rc
        print(
            f"[hw_watch] tunnel dead; retry in {args.interval:.0f}s", flush=True
        )
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
