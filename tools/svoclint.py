#!/usr/bin/env python
"""svoclint — the repo's JAX-hazard static analyzer, as a CI gate.

Usage::

    python tools/svoclint.py svoc_tpu tools                # text report
    python tools/svoclint.py svoc_tpu tools --format json  # machine form
    python tools/svoclint.py svoc_tpu --write-baseline     # grandfather
    python tools/svoclint.py --list-rules

Exit codes: **0** clean (every finding fixed, suppressed, or baselined),
**1** non-baselined findings (or stale baseline entries — baselines only
shrink), **2** usage/internal error.  ``make lint`` runs this over
``svoc_tpu tools`` with the checked-in ``tools/svoclint_baseline.json``.

No JAX import anywhere on this path (enforced by
tests/test_svoclint.py): linting must cost sub-seconds on a CPU-only
box.  Rules and the suppression/baseline workflow are documented in
docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from svoc_tpu.analysis import (  # noqa: E402 (path bootstrap above)
    Baseline,
    RULE_DOCS,
    analyze_paths,
)

# Anchored to the repo (not the CWD): running the linter from another
# directory must still honor the checked-in baseline.
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "svoclint_baseline.json")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="svoclint", description=__doc__.splitlines()[0]
    )
    p.add_argument(
        "paths",
        nargs="*",
        # repo-anchored like DEFAULT_BASELINE: the bare invocation must
        # work from any CWD
        default=[
            os.path.join(REPO_ROOT, "svoc_tpu"),
            os.path.join(REPO_ROOT, "tools"),
        ],
        help="files/directories to analyze (default: the repo's "
        "svoc_tpu and tools trees)",
    )
    p.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="report format (default: text)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help=f"baseline JSON path (default: {DEFAULT_BASELINE} when present)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore any baseline file (report every finding)",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="write all current findings to the baseline file and exit 0",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule table and exit"
    )
    p.add_argument(
        "--root",
        default=REPO_ROOT,
        help="path findings are reported relative to (default: the repo "
        "root, so baseline path keys are stable across CWDs)",
    )
    return p


def _list_rules() -> int:
    for rule_id in sorted(RULE_DOCS):
        doc = RULE_DOCS[rule_id]
        print(f"{rule_id}  {doc['name']:24s} [{doc['severity']}] {doc['summary']}")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()

    for path in args.paths:
        if not os.path.exists(path):
            print(f"svoclint: path does not exist: {path}", file=sys.stderr)
            return 2

    report = analyze_paths(args.paths, root=args.root)
    findings = report.all_findings

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None
    )

    if args.write_baseline:
        out_path = args.baseline or DEFAULT_BASELINE
        # Never grandfather SVOC000: a file the linter cannot parse is
        # analyzed by NO rule, and baselining that would turn "CI must
        # fail loudly" (engine.py) into a permanent silent skip.
        writable = [f for f in findings if f.rule != "SVOC000"]
        skipped = len(findings) - len(writable)
        # Regenerating must not clobber the rest of the baseline: carry
        # curated reasons forward for keys that still match, and keep
        # entries VERBATIM for files outside the analyzed subset (a
        # `--write-baseline` over one tree must not drop another
        # tree's grandfathered entries).
        analyzed = set(report.analyzed_paths)
        old_reasons = {}
        kept_entries = []
        if os.path.exists(out_path):
            try:
                for e in Baseline.load(out_path).entries:
                    if e.get("path") not in analyzed:
                        kept_entries.append(e)
                        continue
                    key = (
                        str(e.get("rule", "")),
                        str(e.get("path", "")),
                        str(e.get("snippet", "")),
                        str(e.get("context", "")),
                    )
                    old_reasons.setdefault(key, e.get("reason", ""))
            except (OSError, ValueError):
                pass
        merged = Baseline()
        for e in kept_entries:
            merged.add(e)
        for f in writable:
            merged.add(
                {
                    "rule": f.rule,
                    "path": f.path,
                    "snippet": f.snippet,
                    "context": f.context,
                    "reason": old_reasons.get(f.baseline_key())
                    or "grandfathered by --write-baseline; triage me",
                }
            )
        merged.dump(out_path)
        print(
            f"svoclint: wrote {len(writable)} finding(s) "
            f"(+{len(kept_entries)} kept for unanalyzed paths) to "
            f"{out_path} ({report.files} files, {report.duration_s:.2f}s)"
        )
        if skipped:
            print(
                f"svoclint: refused to baseline {skipped} SVOC000 "
                "parse-error finding(s) — fix the syntax errors",
                file=sys.stderr,
            )
            return 1
        return 0

    stale = []
    baselined = []
    if baseline_path and not args.no_baseline:
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError) as e:
            print(f"svoclint: bad baseline {baseline_path}: {e}", file=sys.stderr)
            return 2
        findings, baselined, stale = baseline.split(findings)

    if args.format == "json":
        payload = {
            "findings": [f.to_dict() for f in findings],
            "counts": {
                "new": len(findings),
                "baselined": len(baselined),
                "suppressed": report.suppressed,
                "stale_baseline_entries": len(stale),
                "files": report.files,
            },
            "stale_baseline_entries": stale,
            "duration_s": round(report.duration_s, 3),
        }
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.render())
        for entry in stale:
            print(
                f"stale baseline entry (finding no longer present — remove "
                f"it): {entry['rule']} {entry['path']} | {entry['snippet']}"
            )
        status = "clean" if not findings and not stale else "FAILED"
        print(
            f"svoclint: {status} — {len(findings)} new, {len(baselined)} "
            f"baselined, {report.suppressed} suppressed, {len(stale)} stale "
            f"baseline entr{'y' if len(stale) == 1 else 'ies'} "
            f"({report.files} files in {report.duration_s:.2f}s)"
        )

    return 1 if findings or stale else 0


if __name__ == "__main__":
    sys.exit(main())
