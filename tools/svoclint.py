#!/usr/bin/env python
"""svoclint — the repo's JAX-hazard static analyzer, as a CI gate.

Usage::

    python tools/svoclint.py svoc_tpu tools                # text report
    python tools/svoclint.py svoc_tpu tools --format json  # machine form
    python tools/svoclint.py --changed                     # pre-commit loop
    python tools/svoclint.py svoc_tpu --write-baseline     # grandfather
    python tools/svoclint.py --list-rules

Exit codes: **0** clean (every finding fixed, suppressed, or baselined),
**1** non-baselined findings (or stale baseline entries — baselines only
shrink), **2** usage/internal error.  ``make lint`` runs this over
``svoc_tpu tools`` with the checked-in ``tools/svoclint_baseline.json``.

Two speed paths keep iteration sub-second as the repo grows:
``--changed`` lints only files differing from ``git merge-base HEAD
main`` (falling back to the full tree when git is unavailable), and the
content-hash findings cache (``.svoclint_cache.json``, gitignored; keyed
by rule-set version + file sha256) lets warm full runs skip parsing
unchanged files entirely.  The interprocedural and contract-plane
rules (SVOC008–017) run fresh every time over the cached per-module
summaries — their findings carry a ``path_trace`` (the call chain that
justifies the finding) in both text (``via:`` lines) and JSON output,
and ``--sarif <path>`` additionally writes the NEW findings as a SARIF
2.1.0 document (trace hops become ``relatedLocations``) for GitHub
code scanning / editor ingestion.

No JAX import anywhere on this path (enforced by
tests/test_svoclint.py): linting must cost sub-seconds on a CPU-only
box.  Rules and the suppression/baseline workflow are documented in
docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from svoc_tpu.analysis import (  # noqa: E402 (path bootstrap above)
    Baseline,
    RULE_DOCS,
    analyze_paths,
    suggest_rebase,
)
from svoc_tpu.analysis.cache import CACHE_BASENAME  # noqa: E402

# Anchored to the repo (not the CWD): running the linter from another
# directory must still honor the checked-in baseline.
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "svoclint_baseline.json")
DEFAULT_CACHE = os.path.join(REPO_ROOT, CACHE_BASENAME)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="svoclint", description=__doc__.splitlines()[0]
    )
    p.add_argument(
        "paths",
        nargs="*",
        # repo-anchored like DEFAULT_BASELINE: the bare invocation must
        # work from any CWD
        default=[
            os.path.join(REPO_ROOT, "svoc_tpu"),
            os.path.join(REPO_ROOT, "tools"),
        ],
        help="files/directories to analyze (default: the repo's "
        "svoc_tpu and tools trees)",
    )
    p.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="report format (default: text)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help=f"baseline JSON path (default: {DEFAULT_BASELINE} when present)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore any baseline file (report every finding)",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="write all current findings to the baseline file and exit 0",
    )
    p.add_argument(
        "--changed",
        action="store_true",
        help="lint only files differing from `git merge-base HEAD main` "
        "(plus untracked), restricted to the given paths; falls back to "
        "the full tree when git is unavailable.  Stale baseline entries "
        "outside the changed subset are ignored (the full run owns them).",
    )
    p.add_argument(
        "--cache",
        default=DEFAULT_CACHE,
        help="findings-cache path (content-hash keyed; skips re-parsing "
        f"unchanged files; default: {DEFAULT_CACHE})",
    )
    p.add_argument(
        "--no-cache",
        action="store_true",
        help="disable the findings cache for this run",
    )
    p.add_argument(
        "--sarif",
        default=None,
        metavar="PATH",
        help="also write the NEW findings as a SARIF 2.1.0 document to "
        "PATH (path_trace hops become relatedLocations); baselined and "
        "suppressed findings are not exported",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule table and exit"
    )
    p.add_argument(
        "--root",
        default=REPO_ROOT,
        help="path findings are reported relative to (default: the repo "
        "root, so baseline path keys are stable across CWDs)",
    )
    return p


def _list_rules() -> int:
    for rule_id in sorted(RULE_DOCS):
        doc = RULE_DOCS[rule_id]
        print(f"{rule_id}  {doc['name']:32s} [{doc['severity']}] {doc['summary']}")
    return 0


def _git_changed_files(root: str):
    """Repo-root-relative paths of ``*.py`` files differing from the
    merge-base with main (ACMR) plus untracked files, or None when git
    (or the main ref) is unavailable — the caller falls back to the
    full tree, never to silence."""

    def run(cwd, *args):
        proc = subprocess.run(
            ["git", *args], capture_output=True, text=True, cwd=cwd,
            timeout=30,
        )
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr.strip() or "git failed")
        return proc.stdout

    try:
        # git reports diff paths relative to the TOPLEVEL, whatever cwd
        # the command ran from — resolve against it, not args.root, or
        # a non-toplevel --root silently drops every tracked change.
        top = run(root, "rev-parse", "--show-toplevel").strip()
        base = run(top, "merge-base", "HEAD", "main").strip()
        diff = run(
            top, "diff", "--name-only", "--diff-filter=ACMR", base,
            "--", "*.py",
        )
        untracked = run(
            top, "ls-files", "--others", "--exclude-standard", "--", "*.py"
        )
    except (RuntimeError, OSError, subprocess.SubprocessError):
        return None
    files = [l.strip() for l in (diff + untracked).splitlines() if l.strip()]
    return sorted(os.path.join(top, f) for f in set(files))


def _restrict_to_changed(paths, root):
    """``(files, fell_back)``: the changed files under ``paths``, or
    the original paths when git is unavailable."""
    changed = _git_changed_files(root)
    if changed is None:
        print(
            "svoclint: --changed requested but git/main unavailable — "
            "linting the full tree",
            file=sys.stderr,
        )
        return list(paths), True
    roots = [os.path.abspath(p) for p in paths]
    out = []
    for full in changed:  # already absolute (toplevel-joined)
        if not os.path.exists(full):
            continue  # deleted files have nothing to lint
        for r in roots:
            if full == r or full.startswith(r + os.sep):
                out.append(full)
                break
    return out, False


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()

    for path in args.paths:
        if not os.path.exists(path):
            print(f"svoclint: path does not exist: {path}", file=sys.stderr)
            return 2

    paths = list(args.paths)
    changed_subset = False
    if args.changed:
        paths, fell_back = _restrict_to_changed(paths, args.root)
        changed_subset = not fell_back
        if changed_subset and not paths:
            print("svoclint: clean — no changed python files under the "
                  "given paths")
            return 0

    cache_path = None if args.no_cache else args.cache
    report = analyze_paths(paths, root=args.root, cache_path=cache_path)
    findings = report.all_findings

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None
    )

    if args.write_baseline:
        out_path = args.baseline or DEFAULT_BASELINE
        # Never grandfather SVOC000: a file the linter cannot parse is
        # analyzed by NO rule, and baselining that would turn "CI must
        # fail loudly" (engine.py) into a permanent silent skip.
        writable = [f for f in findings if f.rule != "SVOC000"]
        skipped = len(findings) - len(writable)
        # Regenerating must not clobber the rest of the baseline: carry
        # curated reasons forward for keys that still match, and keep
        # entries VERBATIM for files outside the analyzed subset (a
        # `--write-baseline` over one tree must not drop another
        # tree's grandfathered entries).
        analyzed = set(report.analyzed_paths)
        old_reasons = {}
        kept_entries = []
        if os.path.exists(out_path):
            try:
                for e in Baseline.load(out_path).entries:
                    if e.get("path") not in analyzed:
                        kept_entries.append(e)
                        continue
                    key = (
                        str(e.get("rule", "")),
                        str(e.get("path", "")),
                        str(e.get("snippet", "")),
                        str(e.get("context", "")),
                    )
                    old_reasons.setdefault(key, e.get("reason", ""))
            except (OSError, ValueError):
                pass
        merged = Baseline()
        for e in kept_entries:
            merged.add(e)
        for f in writable:
            merged.add(
                {
                    "rule": f.rule,
                    "path": f.path,
                    "snippet": f.snippet,
                    "context": f.context,
                    "reason": old_reasons.get(f.baseline_key())
                    or "grandfathered by --write-baseline; triage me",
                }
            )
        merged.dump(out_path)
        print(
            f"svoclint: wrote {len(writable)} finding(s) "
            f"(+{len(kept_entries)} kept for unanalyzed paths) to "
            f"{out_path} ({report.files} files, {report.duration_s:.2f}s)"
        )
        if skipped:
            print(
                f"svoclint: refused to baseline {skipped} SVOC000 "
                "parse-error finding(s) — fix the syntax errors",
                file=sys.stderr,
            )
            return 1
        return 0

    stale = []
    baselined = []
    if baseline_path and not args.no_baseline:
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError) as e:
            print(f"svoclint: bad baseline {baseline_path}: {e}", file=sys.stderr)
            return 2
        findings, baselined, stale = baseline.split(findings)
        if changed_subset:
            # A --changed run sees only a slice of the tree: entries
            # for files OUTSIDE the slice are not stale, they are
            # simply unobserved — the full run owns their lifecycle.
            analyzed = set(report.analyzed_paths)
            stale = [e for e in stale if e.get("path") in analyzed]

    # Stale-entry diagnostics: the grandfathered statement was usually
    # EDITED, not fixed — name the likely successor so the failure is
    # an actionable one-line rebase instead of an archaeology session.
    all_current = report.all_findings
    suggestions = {
        id(e): suggest_rebase(e, all_current) for e in stale
    }

    if args.sarif:
        from svoc_tpu.analysis.sarif import write_sarif  # noqa: E402

        write_sarif(args.sarif, findings, RULE_DOCS, root=args.root)

    if args.format == "json":
        payload = {
            "findings": [f.to_dict() for f in findings],
            "counts": {
                "new": len(findings),
                "baselined": len(baselined),
                "suppressed": report.suppressed,
                "stale_baseline_entries": len(stale),
                "files": report.files,
                "parsed": report.parsed,
                "cache_hits": report.cache_hits,
            },
            "stale_baseline_entries": [
                dict(
                    e,
                    suggested_rebase=(
                        suggestions[id(e)].to_dict()
                        if suggestions[id(e)] is not None
                        else None
                    ),
                )
                for e in stale
            ],
            "duration_s": round(report.duration_s, 3),
        }
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.render())
        for entry in stale:
            print(
                f"stale baseline entry (finding no longer present — remove "
                f"it): {entry['rule']} {entry['path']} | {entry['snippet']}"
            )
            hint = suggestions[id(entry)]
            if hint is not None:
                print(
                    f"    suggested rebase -> same rule+path at "
                    f"{hint.path}:{hint.line}: | {hint.snippet}\n"
                    "    (update the entry's snippet/context to match, "
                    "or fix the finding and delete the entry)"
                )
        status = "clean" if not findings and not stale else "FAILED"
        print(
            f"svoclint: {status} — {len(findings)} new, {len(baselined)} "
            f"baselined, {report.suppressed} suppressed, {len(stale)} stale "
            f"baseline entr{'y' if len(stale) == 1 else 'ies'} "
            f"({report.files} files, {report.parsed} parsed, "
            f"in {report.duration_s:.2f}s)"
        )

    return 1 if findings or stale else 0


if __name__ == "__main__":
    sys.exit(main())
