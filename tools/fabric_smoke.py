"""Multi-claim fabric smoke: per-claim replay identity + cross-claim
isolation as a CI gate (``make fabric-smoke``; docs/FABRIC.md).

The seeded scenario (:func:`svoc_tpu.fabric.scenario.run_fabric_scenario`
— 4 claims × 7 oracles, the last claim carrying a Byzantine offender
slot) runs TWICE with fresh journals, metrics registries, and a pinned
lineage scope.  The gate asserts:

1. **Per-claim replay identity** — every claim's slice of the journal
   (``fingerprint(lineage_prefix="blkfab-<claim>-")``) digests
   byte-identically across the two runs.  Slices keep their GLOBAL
   seqs, so per-claim identity also certifies the router interleaved
   the claims identically — the scheduling is part of the replay
   witness, not just the math.
2. **Offender handled** — every injected malformed vector was
   quarantined by the offender claim's own gate (verdicts ≥
   injections), and the offender address was voted out through that
   claim's contract.
3. **Isolation** — sibling claims saw ZERO refusing quarantine
   verdicts and ZERO replacements: one claim's poison never crosses
   the claim axis (they share only the accelerator dispatch).
4. **Fair service** — every claim was served every cycle (the scenario
   batch cap covers all claims).

Usage::

    python tools/fabric_smoke.py [--seed 0] [--out FABRIC_SMOKE.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform, so
# go through jax.config too — tools/soak.py measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--cycles", type=int, default=12)
    p.add_argument("--out", default="FABRIC_SMOKE.json")
    args = p.parse_args(argv)

    from svoc_tpu.fabric.scenario import run_fabric_scenario

    first = run_fabric_scenario(args.seed, cycles=args.cycles)
    second = run_fabric_scenario(args.seed, cycles=args.cycles)

    claim_ids = sorted(first["claims"])
    per_claim_identical = {
        cid: (
            first["claims"][cid]["fingerprint"]
            == second["claims"][cid]["fingerprint"]
        )
        for cid in claim_ids
    }
    offender = first["claims"][first["offender_claim"]]
    checks = {
        "per_claim_replay_identical": all(per_claim_identical.values()),
        "journal_replay_identical": (
            first["journal_fingerprint"] == second["journal_fingerprint"]
        ),
        "journal_nonempty": first["journal_events"] > 0,
        "injections_happened": first["injection_count"] > 0,
        # One counted verdict per injected block, none missed (extra
        # verdicts are impossible: honest blocks classify clean).
        "every_injection_quarantined": (
            offender["quarantine_verdicts"] == first["injection_count"]
        ),
        "offender_replaced": first["offender_replaced"],
        "siblings_clean": first["siblings_clean"],
        "all_claims_served_every_cycle": all(
            n == len(claim_ids) for n in first["served_per_step"]
        ),
    }
    ok = all(checks.values())
    artifact = {
        "seed": args.seed,
        "cycles": args.cycles,
        "checks": checks,
        "ok": ok,
        "per_claim_identical": per_claim_identical,
        "offender_claim": first["offender_claim"],
        "offender_address": first["offender_address"],
        "injection_count": first["injection_count"],
        "injections": first["injections"],
        "claims": first["claims"],
        "journal_fingerprint": first["journal_fingerprint"],
        "journal_events": first["journal_events"],
    }
    atomic_write_json(args.out, artifact)
    for name, passed in checks.items():
        print(f"  {'PASS' if passed else 'FAIL'}  {name}")
    print(
        f"fabric-smoke {'OK' if ok else 'FAILED'}: "
        f"{len(claim_ids)} claims × {args.cycles} cycles, "
        f"{first['injection_count']} injections quarantined, "
        f"offender {first['offender_address']} replaced in "
        f"'{first['offender_claim']}' only -> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
