"""Seeded chaos smoke: convergence-under-faults as a CI gate.

Runs the ISSUE-3 acceptance scenario
(:func:`svoc_tpu.resilience.chaos.run_chaos_scenario`) TWICE with the
same seed and asserts:

- **replayable** — the two runs produce bit-identical final contract
  state, replacement history, and fired-fault schedules (the
  fingerprint digest);
- **converged** — the run ends with an active, certified consensus and
  a fully-committed final cycle;
- **no duplicate txs** — resume never re-sent a landed transaction;
- **offender replaced** — the supervisor voted the persistent offender
  out through the contract's replacement flow (exactly once).

Wired into ``make chaos-smoke`` / ``presnapshot`` / ``verify``.  Runs
off-TPU and in seconds: the 7-oracle fleet stays on the per-tx path
(no device work) and all retry timing is virtual.

Usage::

    python tools/chaos_smoke.py [--seed 7] [--cycles 12]
        [--out CHAOS_SMOKE.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform, so
# go through jax.config too — tools/soak.py measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=4)
    p.add_argument("--cycles", type=int, default=12)
    p.add_argument("--out", default="CHAOS_SMOKE.json")
    args = p.parse_args(argv)

    from svoc_tpu.resilience.chaos import run_chaos_scenario

    first = run_chaos_scenario(args.seed, cycles=args.cycles)
    second = run_chaos_scenario(args.seed, cycles=args.cycles)

    checks = {
        "replayable": first["fingerprint"] == second["fingerprint"],
        "consensus_active": bool(first["consensus_active"]),
        "final_cycle_complete": bool(first["final_cycle_complete"]),
        "no_duplicate_txs": first["duplicate_txs"] == 0,
        "offender_replaced": bool(first["offender_replaced"]),
        "exactly_one_replacement": first["replacements"] == 1,
        "faults_actually_fired": first["faults_fired"] > 0,
    }
    ok = all(checks.values())
    artifact = {
        "seed": args.seed,
        "cycles": args.cycles,
        "checks": checks,
        "ok": ok,
        "run": first,
        "replay_fingerprint": second["fingerprint"],
    }
    atomic_write_json(args.out, artifact)
    print(
        json.dumps(
            {
                "chaos_smoke": "ok" if ok else "FAILED",
                "seed": args.seed,
                "checks": checks,
                "faults_fired": first["faults_fired"],
                "replacements": first["replacement_history"],
                "fingerprint": first["fingerprint"][:16],
            }
        ),
        flush=True,
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
