"""Live-mode soak driver (VERDICT r3 item 6).

The reference is an always-on app (``client/main.py:62-63`` parks the
process in eel's event loop; ``oracle_scheduler.py:163-171`` loops
forever); the framework's concurrency layer is well-tested in the small
but this is the long-run proof: ``live_mode on`` (synthetic ingest
source) driving scraper → fetch (REAL packed transformer vectorizer,
random weights) → commit → resume continuously, with periodic
snapshots of RSS, thread count, and the metrics registry.

Writes an incremental JSON artifact (default ``SOAK_r04.json``) so a
killed run still leaves evidence, and exits 0 iff:

- ≥1 snapshot per minute of requested duration landed,
- zero UNEXPECTED errors — faithful on-chain panics
  (``ChainCommitError``: interval error / division-by-zero fleets the
  reference contract rejects identically, ``math.cairo:320-343``) are
  counted separately and allowed at ≤ 2 % of commits, provided the loop
  recovered (commits kept succeeding afterwards),
- RSS was stable (last-quarter median ≤ 1.15 × first-quarter median),
- the background loops wound down cleanly on ``exit`` (thread count
  returns to within 2 of the pre-enable baseline within 30 s).

``--oracles/--failing`` raise the fleet to product scale (1024/256):
every commit then exercises the batched fleet path
(:meth:`svoc_tpu.io.chain.ChainAdapter.update_all_the_predictions`
auto-batching ≥ 64).

Usage::

    python tools/soak.py [--minutes 60] [--refresh 3] [--oracles 7]
        [--failing 2] [--out SOAK_r04.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction: the soak must not touch the (possibly dead)
# tunnel.  The axon sitecustomize pins the TPU platform regardless of
# the env var, so override through jax.config too (ROUND3_NOTES.md
# measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402
import threading  # noqa: E402
import time  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return float("nan")


def median(xs):
    xs = sorted(xs)
    n = len(xs)
    if not n:
        return float("nan")
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def soak_recovered(snaps) -> bool:
    """True iff a commit SUCCEEDED after the last panic (or none
    occurred).  The commit timer counts attempts (it observes in a
    ``finally``) and chain_transactions grows on partial commits too,
    so recovery is read from the snapshot series: successful commits =
    attempts − failures; there must be more of them at the end than at
    the last snapshot where the failure count moved, and the chain must
    still hold an active consensus."""
    if not snaps:
        return False
    if not snaps[-1]["consensus_active"]:
        return False

    def successes(s):
        return s["commits"] - s["chain_commit_failures"]

    last_panic_idx = None
    prev_failures = 0.0
    for i, s in enumerate(snaps):
        if s["chain_commit_failures"] > prev_failures:
            last_panic_idx = i
            prev_failures = s["chain_commit_failures"]
    if last_panic_idx is None:
        return True
    return successes(snaps[-1]) > successes(snaps[last_panic_idx])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--minutes", type=float, default=60.0)
    p.add_argument("--refresh", type=float, default=3.0, help="fetch period s")
    p.add_argument("--scraper-rate", type=float, default=7.0)
    p.add_argument("--snapshot-every", type=float, default=60.0)
    p.add_argument("--oracles", type=int, default=7)
    p.add_argument("--failing", type=int, default=2)
    p.add_argument("--out", default="SOAK_r04.json")
    p.add_argument(
        "--chaos-seed",
        type=int,
        default=None,
        help=(
            "enable deterministic fault injection on the chain backend "
            "(transient commit faults on 2 oracles + one persistent "
            "offender the supervisor must vote out — docs/RESILIENCE.md)"
        ),
    )
    args = p.parse_args(argv)

    from svoc_tpu.apps.commands import CommandConsole
    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.utils.metrics import (
        compile_snapshot,
        install_compile_listener,
        registry,
    )

    # Compile-plane series must start counting BEFORE the first jit —
    # the listener is process-global and on-demand elsewhere.
    install_compile_listener()

    # The real packed transformer pipeline, with workload conditioning:
    # random weights (no HF cache in the image) map every text to a
    # near-identical vector, and a fleet of near-identical predictions
    # drives the contract's sample variance to exactly 0 in wsad fixed
    # point — where BOTH this engine and the reference contract panic
    # with division-by-zero in skewness/kurtosis
    # (``math.cairo:320-343`` divides by sqrt(variance) unguarded; see
    # tests/test_state.py::test_zero_variance_panics_like_cairo).  Real
    # weights produce varied vectors, so the soak mixes in a small
    # deterministic text-dependent component to keep the workload
    # realistic while still paying the full model forward every fetch.
    from svoc_tpu.models.sentiment import SentimentPipeline

    model = SentimentPipeline(packed=True)

    def conditioned_vectorizer(texts):
        import numpy as np

        v = np.asarray(model(texts), dtype=np.float64)
        rng = np.random.default_rng(
            [hash(t) % (2**32) for t in texts] or [0]
        )
        noise = rng.uniform(0.05, 0.95, size=v.shape)
        mixed = 0.7 * v + 0.3 * noise
        return mixed / mixed.sum(axis=1, keepdims=True)

    config = SessionConfig(
        refresh_rate_s=args.refresh,
        scraper_rate_s=args.scraper_rate,
        n_oracles=args.oracles,
        n_failing=args.failing,
    )
    adapter = None
    if args.chaos_seed is not None:
        # Chaos soak: the session's local backend wrapped in the seeded
        # fault injector (the same spec mix `make chaos-smoke` replays),
        # so the long run exercises retry/resume/breaker/supervisor.
        from svoc_tpu.apps.session import _default_contract
        from svoc_tpu.io.chain import ChainAdapter, LocalChainBackend
        from svoc_tpu.resilience.faults import (
            FaultInjectingBackend,
            FaultPlan,
            standard_fault_specs,
        )

        oracle_addrs = [0x10 + i for i in range(args.oracles)]
        plan = FaultPlan(
            args.chaos_seed,
            standard_fault_specs(
                transient=oracle_addrs[: min(2, args.oracles - 1)],
                persistent=oracle_addrs[-1:],
            ),
        )
        adapter = ChainAdapter(
            FaultInjectingBackend(
                LocalChainBackend(_default_contract(config)), plan
            )
        )

    session = Session(
        config=config,
        store=CommentStore(),  # empty: the scraper is the only ingest
        vectorizer=conditioned_vectorizer,
        adapter=adapter,
    )
    console_lines = []
    console = CommandConsole(session, write=console_lines.append)

    # Flight recorder (docs/OBSERVABILITY.md §events): the soak rides
    # the process-wide journal; the postmortem monitor auto-bundles on
    # incident-class events (breaker open, quarantine spike, invalid
    # interval) so a failing soak leaves evidence beyond the snapshots.
    from svoc_tpu.utils.events import journal
    from svoc_tpu.utils.postmortem import PostmortemMonitor

    monitor = PostmortemMonitor(
        out_dir=".", session=session, max_bundles=4
    ).install()

    baseline_threads = threading.active_count()
    t0 = time.time()
    artifact = {
        "started_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "minutes_requested": args.minutes,
        "refresh_rate_s": args.refresh,
        "scraper_rate_s": args.scraper_rate,
        "n_oracles": args.oracles,
        "n_failing": args.failing,
        "vectorizer": (
            "SentimentPipeline(packed=True) [random weights] + 0.3 "
            "text-hash mix (workload conditioning, see source comment)"
        ),
        "baseline_threads": baseline_threads,
        "snapshots": [],
    }

    def flush():
        atomic_write_json(args.out, artifact)

    console.query("auto_resume on")
    out = console.query("live_mode on")
    print("\n".join(out), flush=True)
    assert any("Live mode: ENABLED" in line for line in out), out

    end = t0 + args.minutes * 60.0
    next_snap = t0 + args.snapshot_every
    try:
        while time.time() < end:
            time.sleep(min(5.0, max(0.0, next_snap - time.time())))
            if time.time() < next_snap:
                continue
            next_snap += args.snapshot_every
            fetch_t = registry.timer("fetch_latency")
            commit_t = registry.timer("commit_latency")
            # Percentiles come from the SAME per-stage histograms the
            # session's spans feed and /metrics exposes
            # (svoc_tpu/utils/metrics.py) — the soak artifact and live
            # telemetry are one data set, never two clocks.
            fetch_h = registry.stage_histogram("fetch")
            commit_h = registry.stage_histogram("commit")
            snap = {
                "elapsed_s": round(time.time() - t0, 1),
                "rss_mb": round(rss_mb(), 1),
                "threads": threading.active_count(),
                "store_comments": session.store.count(),
                "state_version": session.state_version,
                "fetches": fetch_t.n,
                "fetch_mean_ms": round(fetch_t.mean_s * 1e3, 1),
                "fetch_max_ms": round(fetch_t.max_s * 1e3, 1),
                "fetch_p50_ms": round(fetch_h.percentile(50) * 1e3, 1),
                "fetch_p95_ms": round(fetch_h.percentile(95) * 1e3, 1),
                "fetch_p99_ms": round(fetch_h.percentile(99) * 1e3, 1),
                "commits": commit_t.n,
                "commit_mean_ms": round(commit_t.mean_s * 1e3, 1),
                "commit_p95_ms": round(commit_h.percentile(95) * 1e3, 1),
                "comments_processed": registry.counter(
                    "comments_processed"
                ).count,
                "chain_transactions": registry.counter(
                    "chain_transactions"
                ).count,
                "auto_fetch_errors": registry.counter(
                    "auto_fetch_errors"
                ).count,
                "chain_commit_failures": registry.counter(
                    "chain_commit_failures"
                ).count,
                # Resilience series (docs/RESILIENCE.md): the same
                # counters/gauges GET /metrics exposes.
                "faults_injected": registry.family_total("faults_injected"),
                "retries": registry.family_total("retries"),
                "commit_resumes": registry.counter("commit_resumes").count,
                "commit_stranded": registry.counter("commit_stranded").count,
                "oracle_replacements": registry.counter(
                    "oracle_replacements"
                ).count,
                "breaker_state": session.breaker.state(),
                "quarantined_slots": session.supervisor.quarantined_slots(),
                "consensus_active": bool(
                    session.adapter.cache.get("consensus_active")
                ),
                # Flight-recorder pulse: total journaled events + the
                # live SLO alert count, so the snapshot series shows
                # WHEN the story turned, not just how fast it ran.
                "journal_events": journal.last_seq(),
                "slo_alerts": registry.family_total("slo_alerts"),
                "trace_write_errors": registry.counter(
                    "trace_write_errors"
                ).count,
                # Compile plane (docs/PARALLELISM.md §compile-plane):
                # fresh XLA compiles + persistent-cache hit/miss over
                # the run — a soak that keeps compiling is a shape leak.
                "xla_compiles": registry.counter(
                    "xla_compiles_total"
                ).count,
                "xla_cache_misses": registry.counter(
                    "xla_cache_events", labels={"event": "miss"}
                ).count,
            }
            artifact["snapshots"].append(snap)
            flush()
            print(f"[soak] {json.dumps(snap)}", flush=True)
    finally:
        # Clean shutdown through the command surface, like a user would.
        print("\n".join(console.query("live_mode off")), flush=True)
        print("\n".join(console.query("exit")), flush=True)
        deadline = time.time() + 30.0
        while (
            threading.active_count() > baseline_threads + 2
            and time.time() < deadline
        ):
            time.sleep(0.5)
        wind_down_threads = threading.active_count()

        snaps = artifact["snapshots"]
        q = max(1, len(snaps) // 4)
        rss_first = median([s["rss_mb"] for s in snaps[:q]])
        rss_last = median([s["rss_mb"] for s in snaps[-q:]])
        # Error taxonomy: with the resilient commit path (PR 3) chain
        # panics and flaky txs are handled INSIDE commit_resilient —
        # retried, resumed, or stranded — and show up as
        # chain_commit_failures (degraded cycles), never as auto-loop
        # errors.  auto_fetch_errors is therefore the pure UNEXPECTED
        # class now (framework bugs, deadline-expired commits).
        error_lines = [
            line for line in console_lines if line.startswith("auto_fetch error")
        ]
        chain_panics = int(registry.counter("chain_commit_failures").count)
        unexpected = int(registry.counter("auto_fetch_errors").count)
        commits = registry.timer("commit_latency").n
        panic_rate = chain_panics / max(commits, 1)
        recovered = soak_recovered(snaps)
        enough_snaps = len(snaps) >= int(args.minutes) * max(
            1, int(60 / args.snapshot_every)
        )
        rss_stable = bool(snaps) and rss_last <= rss_first * 1.15
        clean_exit = (
            wind_down_threads <= baseline_threads + 2
            and session.application_on is False
        )
        # Chaos soaks deliberately degrade commits until the supervisor
        # replaces the persistent offender: budget the early degraded
        # cycles, and require the replacement actually happened.
        panic_budget = 0.02 if args.chaos_seed is None else 0.25
        chaos_ok = args.chaos_seed is None or (
            registry.counter("oracle_replacements").count >= 1
        )
        artifact["summary"] = {
            "elapsed_s": round(time.time() - t0, 1),
            "snapshots": len(snaps),
            "fetches": registry.timer("fetch_latency").n,
            "commits": commits,
            # End-of-run stage percentiles from the shared registry —
            # the same series a live /metrics scrape would have shown.
            "stage_seconds": registry.stage_snapshot(),
            "comments_processed": registry.counter(
                "comments_processed"
            ).count,
            "chain_transactions": registry.counter(
                "chain_transactions"
            ).count,
            "unexpected_errors": unexpected,
            "chain_panics": chain_panics,
            "chain_panic_rate": round(panic_rate, 4),
            "recovered_after_panics": recovered,
            # Resilience totals (docs/RESILIENCE.md): fault/retry/
            # replacement accounting for the whole run.
            "faults_injected": registry.family_total("faults_injected"),
            "retries": registry.family_total("retries"),
            "commit_resumes": registry.counter("commit_resumes").count,
            "commit_stranded": registry.counter("commit_stranded").count,
            "oracle_replacements": registry.counter(
                "oracle_replacements"
            ).count,
            "breaker_state": session.breaker.state(),
            "replacement_history": list(session.supervisor.replacements),
            # Journal digest (counts by type, last alerts, fingerprint)
            # + any auto-built postmortem bundles: the artifact answers
            # "what happened", not just "how fast" (ISSUE 5 satellite).
            "journal": journal.summary(),
            "slo": session.slo_step(),
            "postmortem_bundles": list(monitor.bundles),
            # End-of-run compile-plane digest (ISSUE 15 satellite): the
            # xla_compile_seconds histogram + cache hit/miss totals the
            # jax.monitoring listener fed over the whole soak.
            "compile": compile_snapshot(),
            "chaos_seed": args.chaos_seed,
            "rss_mb_first_quarter_median": rss_first,
            "rss_mb_last_quarter_median": rss_last,
            "rss_stable": rss_stable,
            "clean_exit": clean_exit,
            "threads_after_exit": wind_down_threads,
            "ok": bool(
                enough_snaps
                and unexpected == 0
                and panic_rate <= panic_budget
                and chaos_ok
                and recovered
                and rss_stable
                and clean_exit
            ),
        }
        artifact["error_lines"] = error_lines
        # Last console lines for general context.
        artifact["console_tail"] = console_lines[-10:]
        flush()
        print(f"[soak] summary: {json.dumps(artifact['summary'])}", flush=True)
    return 0 if artifact["summary"]["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
