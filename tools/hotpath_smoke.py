"""Zero-sync hot-path smoke: the host-overhead optimizations as a CI
gate (``make hotpath-smoke``; docs/PARALLELISM.md §host-overhead).

The seeded 4-claim fabric scenario runs TWICE with the optimized hot
path pinned on — device-resident staging + donated dispatch
(``device_resident=True``) and the batched commit plane
(``commit_mode="batched"``) — plus ONE unoptimized control run.  The
gate asserts:

1. **Replay identity under optimization** — the two optimized runs'
   per-claim journal fingerprints digest byte-identically.
2. **Not a fingerprint family** — the optimized fingerprints equal the
   unoptimized control's (the shard-smoke meshed==unmeshed precedent):
   staging/donation are bit-identical numerics and the batched commit
   plane emits the per-tx plane's exact journal events, so the
   optimizations must be invisible to seeded replays.
3. **Counted, never-silent fallbacks** — the scenario's quarantined
   cycles force tx granularity on the offender claim, and every such
   degradation shows up in ``commit_batch_fallback{reason=
   "skip_slots"}``.
4. **N→1 RPCs** — a clean (quarantine-free) 4-claim × 8-oracle leg
   commits C·cycles batched RPCs and ZERO per-tx RPCs: the chain pays
   one commit RPC per claim-cycle, not one per oracle.

Usage::

    python tools/hotpath_smoke.py [--seed 0] [--out HOTPATH_SMOKE.json]
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402


def clean_leg_rpcs(seed: int, claims: int, cycles: int, oracles: int):
    """Quarantine-free batched fabric leg; returns the process-registry
    commit-RPC deltas (the adapter counts RPCs globally by design —
    seeded replays don't fingerprint metrics)."""
    from svoc_tpu.fabric.registry import ClaimSpec
    from svoc_tpu.fabric.scenario import (
        _claim_names,
        deterministic_vectorizer,
    )
    from svoc_tpu.fabric.session import MultiSession
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource
    from svoc_tpu.sim.generators import claim_seed
    from svoc_tpu.utils.events import EventJournal
    from svoc_tpu.utils.metrics import MetricsRegistry
    from svoc_tpu.utils.metrics import registry as process_registry

    def store_factory(claim_id: str) -> CommentStore:
        store = CommentStore()
        store.save(
            SyntheticSource(batch=100, seed=claim_seed(seed, claim_id))()
        )
        return store

    multi = MultiSession(
        base_seed=seed,
        vectorizer=deterministic_vectorizer,
        store_factory=store_factory,
        journal=EventJournal(),
        metrics=MetricsRegistry(),
        lineage_scope="hps",
        max_claims_per_batch=claims,
        device_resident=True,
        commit_mode="batched",
    )
    for name in _claim_names(claims):
        multi.add_claim(ClaimSpec(claim_id=name, n_oracles=oracles))

    def counts():
        return {
            mode: process_registry.counter(
                "chain_commit_rpcs", labels={"mode": mode}
            ).count
            for mode in ("tx", "batch")
        }

    before = counts()
    multi.run(cycles)
    after = counts()
    return {mode: after[mode] - before[mode] for mode in after}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--cycles", type=int, default=10)
    p.add_argument("--out", default="HOTPATH_SMOKE.json")
    args = p.parse_args(argv)

    from svoc_tpu.fabric.scenario import run_fabric_scenario
    from svoc_tpu.utils.metrics import registry as process_registry

    def fallback_count() -> float:
        return process_registry.counter(
            "commit_batch_fallback", labels={"reason": "skip_slots"}
        ).count

    fallbacks_before = fallback_count()
    opt1 = run_fabric_scenario(
        args.seed, cycles=args.cycles,
        device_resident=True, commit_mode="batched",
    )
    opt2 = run_fabric_scenario(
        args.seed, cycles=args.cycles,
        device_resident=True, commit_mode="batched",
    )
    fallbacks_delta = fallback_count() - fallbacks_before
    control = run_fabric_scenario(args.seed, cycles=args.cycles)

    claim_ids = sorted(opt1["claims"])
    rpc_claims, rpc_cycles, rpc_oracles = 4, 4, 8
    rpcs = clean_leg_rpcs(args.seed, rpc_claims, rpc_cycles, rpc_oracles)

    checks = {
        "optimized_replay_identical": all(
            opt1["claims"][c]["fingerprint"]
            == opt2["claims"][c]["fingerprint"]
            for c in claim_ids
        )
        and opt1["journal_fingerprint"] == opt2["journal_fingerprint"],
        "optimized_equals_unoptimized": all(
            opt1["claims"][c]["fingerprint"]
            == control["claims"][c]["fingerprint"]
            for c in claim_ids
        )
        and opt1["journal_fingerprint"] == control["journal_fingerprint"],
        "injections_happened": opt1["injection_count"] > 0,
        "quarantine_fallbacks_counted": fallbacks_delta > 0,
        # One commit RPC per claim-cycle on the clean leg — C, not C×N.
        "rpcs_batch_is_claim_cycles": (
            rpcs["batch"] == rpc_claims * rpc_cycles
        ),
        "rpcs_tx_is_zero": rpcs["tx"] == 0,
    }
    ok = all(checks.values())
    artifact = {
        "seed": args.seed,
        "cycles": args.cycles,
        "checks": checks,
        "ok": ok,
        "clean_leg": {
            "claims": rpc_claims,
            "cycles": rpc_cycles,
            "oracles": rpc_oracles,
            "rpcs": rpcs,
        },
        "skip_slot_fallbacks": fallbacks_delta,
        "journal_fingerprint": opt1["journal_fingerprint"],
        "per_claim_fingerprints": {
            c: opt1["claims"][c]["fingerprint"] for c in claim_ids
        },
    }
    atomic_write_json(args.out, artifact)
    for name, passed in checks.items():
        print(f"  {'PASS' if passed else 'FAIL'}  {name}")
    print(
        f"hotpath-smoke {'OK' if ok else 'FAILED'}: "
        f"{len(claim_ids)} claims × {args.cycles} cycles optimized twice "
        f"+ control, fingerprints identical, clean leg "
        f"{int(rpcs['batch'])} batched RPCs for "
        f"{rpc_claims * rpc_cycles} claim-cycles -> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
