"""Real-weights accuracy parity harness (VERDICT r3 item 3).

The reference classifies with the pretrained HF pipeline
``SamLowe/roberta-base-go_emotions`` (``client/oracle_scheduler.py:
23-40``); this framework's converter is logit-parity-tested against a
tiny random model only (no HF cache in the build image).  This harness
is the proof that fires the moment real weights are available:

1. load the cached HF torch model + tokenizer (``local_files_only`` —
   never the network) and compute the REFERENCE tracked vectors for the
   committed 30-comment fixture (sigmoid → 6 tracked labels →
   sum-normalize, the exact ``prediction_to_vector`` math),
2. convert the same checkpoint through
   :func:`svoc_tpu.models.convert.load_hf_checkpoint` and run the
   fixture through every serving path — float (unpacked), packed×dense,
   packed×flash, and W8A8 int8 —
3. report per-path max-abs tracked-vector deltas vs the HF reference
   and write ``WEIGHTS_PARITY.json``.

Exit 0 iff the float paths agree with HF within ``--tol`` (default
2e-3 on sum-normalized 6-vectors — bf16-free f32 forward) and int8
within ``--tol-int8`` (default 0.05, the dryrun section-8 accuracy
budget, now measured against REAL weights instead of random ones).

Runs on CPU or TPU (the parity claim is dtype-for-dtype identical
math, not speed).  Skips cleanly (exit 3) when the cache has no model.

Usage::

    python tools/weights_parity.py [--model SamLowe/roberta-base-go_emotions]
        [--tol 2e-3] [--tol-int8 0.05] [--out WEIGHTS_PARITY.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FIXTURE = os.path.join(REPO, "tests", "fixtures", "comments_30.json")


def load_fixture() -> list:
    with open(FIXTURE) as f:
        return json.load(f)["comments"]


def hf_reference_vectors(model_name: str, comments, tracked, seq_len: int):
    """The reference pipeline's tracked vectors, computed with torch —
    raises when the model is not in the local cache."""
    import numpy as np
    import torch
    from transformers import AutoModelForSequenceClassification, AutoTokenizer

    tok = AutoTokenizer.from_pretrained(model_name, local_files_only=True)
    model = AutoModelForSequenceClassification.from_pretrained(
        model_name, local_files_only=True
    )
    model.eval()
    with torch.no_grad():
        enc = tok(
            list(comments),
            padding="max_length",
            truncation=True,
            max_length=seq_len,
            return_tensors="pt",
        )
        logits = model(**enc).logits
        scores = torch.sigmoid(logits).numpy()
    sel = scores[:, list(tracked)]
    return sel / sel.sum(axis=1, keepdims=True), np.asarray(logits)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="SamLowe/roberta-base-go_emotions")
    p.add_argument("--tol", type=float, default=2e-3)
    p.add_argument("--tol-int8", type=float, default=0.05)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--out", default=os.path.join(REPO, "WEIGHTS_PARITY.json"))
    args = p.parse_args(argv)

    import numpy as np

    from svoc_tpu.models.sentiment import TRACKED_INDICES

    comments = load_fixture()
    try:
        ref_vecs, ref_logits = hf_reference_vectors(
            args.model, comments, TRACKED_INDICES, args.seq_len
        )
    except Exception as e:
        print(
            f"SKIP: HF model {args.model!r} not loadable from the local "
            f"cache ({type(e).__name__}: {e}) — the harness proves parity "
            "the moment weights are present",
            flush=True,
        )
        return 3

    from dataclasses import replace

    from svoc_tpu.models.convert import load_hf_checkpoint
    from svoc_tpu.models.sentiment import SentimentPipeline

    model, params = load_hf_checkpoint(args.model)
    cfg = model.cfg

    def pipe(**kw):
        return SentimentPipeline(
            cfg=kw.pop("cfg", cfg),
            params=params,
            seq_len=args.seq_len,
            batch_size=32,
            tokenizer_name=args.model,
            **kw,
        )

    paths = {
        "float": pipe(),
        "packed_dense": pipe(packed=True),
        "packed_flash": pipe(cfg=replace(cfg, attention="flash"), packed=True),
        "int8_packed": pipe(packed=True, quant="int8"),
    }

    report = {
        "model": args.model,
        "n_comments": len(comments),
        "tracked_indices": list(TRACKED_INDICES),
        "hf_logits_mean_abs": float(np.mean(np.abs(ref_logits))),
        "paths": {},
    }
    failures = []
    for name, pl in paths.items():
        got = np.asarray(pl(comments), dtype=np.float64)
        delta = float(np.max(np.abs(got - ref_vecs)))
        tol = args.tol_int8 if name.startswith("int8") else args.tol
        ok = delta <= tol
        report["paths"][name] = {
            "max_abs_delta_vs_hf": delta,
            "tol": tol,
            "ok": ok,
        }
        if not ok:
            failures.append(name)
        print(f"[parity] {name}: max|Δ| = {delta:.2e} (tol {tol:g}) "
              f"{'OK' if ok else 'FAIL'}", flush=True)

    # The int8 accuracy COST is the delta vs our own float path — the
    # quantization question, separated from converter fidelity.
    float_vecs = np.asarray(paths["float"](comments), dtype=np.float64)
    int8_vecs = np.asarray(paths["int8_packed"](comments), dtype=np.float64)
    report["int8_cost_vs_float_max_abs"] = float(
        np.max(np.abs(int8_vecs - float_vecs))
    )

    report["ok"] = not failures
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"[parity] wrote {args.out}; ok={report['ok']}", flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
