"""One-shot hardware measurement queue for tunnel-outage recovery.

The axon TPU tunnel has multi-hour outages (ROUND3_NOTES.md); this
script captures EVERY pending on-chip measurement the moment it is
back, each in its own subprocess (a wedged backend costs one item, not
the run), writing incremental results to ``HW_QUEUE_RESULTS.json``:

1. liveness  — fetch-proven matmul checksum (aborts the queue early
   when the tunnel is still dead, leaving the artifact saying so);
2. tpu_probe — regenerates ``TPU_PROBE.json`` (dense vs pallas probes);
3. flash_probe — regenerates ``FLASH_PROBE.json`` (fwd+bwd timings);
4. bench --config 6  — the pallas-vs-XLA consensus decision number
   (VERDICT round-2 item 5);
5. bench --config 0  — fresh honest flagship;
6. bench --config 8/12/9/10/11 — packed, packed×flash, packed×dp,
   int8, int8×packed×dp.

Usage::

    python tools/hw_queue.py [--seconds 10] [--skip-probes]

Every bench line is parsed and appended as soon as it lands; rerunning
overwrites the artifact.  Exit code 0 iff the liveness check passed,
every queued item exited 0, and every bench item yielded its JSON
result line.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "HW_QUEUE_RESULTS.json")

# Window + generous compile/warmup/probe margin — a fixed cap would
# spuriously kill long --seconds windows.  Shared with
# tools/hw_campaign.py so the margin cannot drift between the two.
BENCH_TIMEOUT_MARGIN_S = 1800.0


def bench_cmd(cfg: int, seconds: float):
    """argv tail (no interpreter) for one bench config measurement."""
    return ["bench.py", "--config", str(cfg), "--seconds", str(seconds)]

LIVENESS_SNIPPET = (
    "import jax, jax.numpy as jnp, numpy as np;"
    "assert jax.devices()[0].platform == 'tpu', jax.devices();"
    "x = jnp.ones((1024, 1024), jnp.bfloat16);"
    "s = float(np.asarray(jnp.sum(jax.jit(lambda a: a @ a)(x))));"
    "print('LIVE', s)"
)


def run_item(name: str, cmd, timeout_s: float):
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd,
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        out = {
            "name": name,
            "rc": proc.returncode,
            "seconds": round(time.time() - t0, 1),
            "captured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
            "stdout_tail": proc.stdout.strip().splitlines()[-3:],
        }
        if proc.returncode != 0:
            out["stderr_tail"] = proc.stderr.strip().splitlines()[-5:]
        # bench lines are single-line JSON — parse when present.
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                try:
                    out["result"] = json.loads(line)
                except json.JSONDecodeError:
                    pass
                break
        # A result produced on the CPU fallback (tunnel died mid-queue)
        # is NOT the hardware measurement this queue exists to capture
        # — mark the otherwise-successful item failed so all_ok stays
        # honest.  A real nonzero exit keeps its own rc: that failure
        # cause must not be masked by the fallback label.  A
        # campaign-replay line (bench.py recycling an earlier capture
        # when its fresh probe failed) is equally NOT a new
        # measurement: without this check the replay would be recorded
        # as a fresh rc=0 TPU result and one old capture could
        # recirculate forever through the journal.
        detail = out.get("result", {}).get("detail", {})
        if out["rc"] == 0 and (
            detail.get("backend_fallback")
            or detail.get("small_mode_auto")
            or detail.get("replayed_from")
        ):
            out["rc"] = "cpu-fallback"
        return out
    except subprocess.TimeoutExpired as e:
        # Keep the partial output — it is the only evidence telling a
        # dead tunnel apart from e.g. a hung pallas compile.
        def tail(stream):
            text = (stream or b"").decode(errors="replace") if isinstance(
                stream, bytes
            ) else (stream or "")
            return text.strip().splitlines()[-5:]

        return {
            "name": name,
            "rc": "timeout",
            "seconds": round(time.time() - t0, 1),
            "stdout_tail": tail(e.stdout),
            "stderr_tail": tail(e.stderr),
        }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seconds", type=float, default=10.0, help="bench window")
    p.add_argument(
        "--skip-probes",
        action="store_true",
        help="only the bench configs (probes already fresh)",
    )
    args = p.parse_args(argv)
    py = sys.executable

    results = {"started_at": time.strftime("%Y-%m-%d %H:%M:%S"), "items": []}

    def record(item):
        results["items"].append(item)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)
        tail = item.get("result", {}).get("value", item.get("rc"))
        print(f"[hw_queue] {item['name']}: {tail} ({item['seconds']}s)", flush=True)

    live = run_item("liveness", [py, "-c", LIVENESS_SNIPPET], 240)
    record(live)
    if live["rc"] != 0:
        print("[hw_queue] tunnel still dead — aborting queue", flush=True)
        return 1

    queue = []
    if not args.skip_probes:
        queue += [
            ("tpu_probe", [py, "tools/tpu_probe.py"], 900),
            ("flash_probe", [py, "tools/flash_probe.py"], 1200),
        ]
    bench_timeout = args.seconds + BENCH_TIMEOUT_MARGIN_S
    for cfg in (6, 0, 8, 12, 9, 10, 11):
        queue.append(
            (
                f"bench_config{cfg}",
                [py] + bench_cmd(cfg, args.seconds),
                bench_timeout,
            )
        )
    for name, cmd, timeout_s in queue:
        record(run_item(name, cmd, timeout_s))

    ok = all(
        i["rc"] == 0 and ("bench" not in i["name"] or "result" in i)
        for i in results["items"]
    )
    print(f"[hw_queue] done, all_ok={ok}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
