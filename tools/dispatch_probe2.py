#!/usr/bin/env python
"""Probe 2: on the axon tunnel, ``block_until_ready`` returns before the
device has executed (probe 1: 0.15 ms for a 5.7-TFLOP forward).  Find a
timing method that reflects real execution: force a host fetch of (a
scalar reduced from) the result each call, and separately measure the
fetch-only cost of an already-computed buffer to bound the D2H overhead.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def med_ms(fn, reps=12, warm=2):
    for _ in range(warm):
        fn()
    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        out.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(out)), [round(x, 3) for x in sorted(out)]


def main():
    result = {"backend": jax.default_backend()}

    from svoc_tpu.models.configs import ROBERTA_GO_EMOTIONS
    from svoc_tpu.models.sentiment import SentimentPipeline

    B, S = 256, 128
    pipe = SentimentPipeline(
        cfg=ROBERTA_GO_EMOTIONS, seq_len=S, batch_size=B, tokenizer_name=None
    )
    fwd = pipe.forward_fn()
    rng = np.random.default_rng(0)
    n_uniq = 8
    ids_pool = [
        jax.device_put(jnp.asarray(rng.integers(10, 5000, (B, S)), jnp.int32))
        for _ in range(n_uniq)
    ]
    mask = jax.device_put(jnp.ones((B, S), jnp.int32))
    out0 = fwd(pipe.params, ids_pool[0], mask)
    _ = np.asarray(out0)  # full warm: compile + execute + fetch

    # fetch-only cost of an existing (already computed+fetched) buffer
    m, s = med_ms(lambda: np.asarray(out0))
    result["refetch_existing_ms"] = round(m, 3)

    # fetch cost of a tiny fresh buffer (trivial op + float())
    f1 = jax.jit(lambda x: x + 1.0)
    xs = [jnp.full((), float(i)) for i in range(50)]
    k = [0]

    def tiny_fetch():
        k[0] += 1
        return float(f1(xs[k[0] % 50]))

    m, s = med_ms(tiny_fetch, reps=20)
    result["tiny_roundtrip_ms"] = round(m, 3)

    # forward + scalar-fetch per call, unique inputs
    j = [0]

    def fwd_fetch():
        j[0] += 1
        return float(jnp.sum(fwd(pipe.params, ids_pool[j[0] % n_uniq], mask)))

    m, s = med_ms(fwd_fetch, reps=12)
    result["fwd_unique_fetch_ms"] = round(m, 3)
    result["fwd_unique_fetch_samples_ms"] = s

    # forward + scalar-fetch, SAME input every call (does the backend
    # cache identical executions?)
    def fwd_fetch_same():
        return float(jnp.sum(fwd(pipe.params, ids_pool[0], mask)))

    m, s = med_ms(fwd_fetch_same, reps=12)
    result["fwd_same_fetch_ms"] = round(m, 3)
    result["fwd_same_fetch_samples_ms"] = s

    # pipelined: dispatch K unique forwards, then fetch all results --
    # the realistic serving pattern (overlap dispatch with execution)
    K = 16
    def pipelined():
        outs = []
        for i in range(K):
            j[0] += 1
            outs.append(fwd(pipe.params, ids_pool[j[0] % n_uniq], mask))
        return [float(jnp.sum(o)) for o in outs]

    t0 = time.perf_counter()
    pipelined()
    warm_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    pipelined()
    result["pipelined_16_fwd_s"] = round(time.perf_counter() - t0, 3)
    result["pipelined_per_fwd_ms"] = round(
        (time.perf_counter() - t0) / K * 1e3, 3
    )

    flops = 256 * 128 * 12 * (2 * (4 * 768 * 768 + 2 * 768 * 3072) + 4 * 128 * 768)
    result["fwd_matmul_tflop"] = round(flops / 1e12, 3)
    per_fwd_s = result["pipelined_16_fwd_s"] / K
    result["pipelined_implied_tflops"] = round(flops / per_fwd_s / 1e12, 1)
    result["pipelined_implied_mfu"] = round(
        result["pipelined_implied_tflops"] / 197.0, 3
    )
    fetch_s = result["fwd_unique_fetch_ms"] / 1e3
    result["fetch_implied_tflops"] = round(flops / fetch_s / 1e12, 1)
    result["fetch_implied_mfu"] = round(result["fetch_implied_tflops"] / 197.0, 3)

    line = json.dumps(result)
    print(line, flush=True)
    with open("DISPATCH_PROBE2.json", "w") as fh:
        fh.write(line + "\n")


if __name__ == "__main__":
    main()
