"""Multi-replica fleet chaos gate: failover as CI (``make cluster-smoke``;
docs/CLUSTER.md, docs/RESILIENCE.md §failover-runbook).

One seeded 3-replica × 6-claim scenario
(:func:`svoc_tpu.cluster.scenario.run_cluster_scenario`), run TWICE in
fresh work directories with an identical schedule:

- one replica is killed mid-run (SIGKILL semantics at a step boundary —
  the ``replica.kill`` registry point) and failed over two steps later
  (recover-then-migrate over its durable dirs);
- one injected forwarding fault (``error`` @ ``cluster.forward.pre_send``)
  that the per-replica retry/breaker plane must absorb;
- one stale-epoch probe (typed redirect) and one down-replica probe
  (typed ``cluster.unavailable`` shed) aimed into the outage window.

Asserted over the results:

- **replay identity** — byte-identical per-claim fingerprints AND the
  fleet fingerprint across the two runs (the digests fold every
  forwarding, shed, redirect, migration, and failover decision);
- **failover served** — the killed replica's claims are owned by
  survivors at the end, with lineage continuity through every
  migration and their chain logs still growing;
- **zero duplicate txs** across the cluster-shared chain logs;
- **zero unaccounted admitted requests** fleet-wide (at-least-once
  accounting; recovered durable counts are the authority for the dead
  replica — the PR 8 convention);
- **coverage** — all five cluster fault points witnessed in the
  durable fired log, and the injected error action executed.

Usage::

    python tools/cluster_smoke.py [--seed 0] [--out CLUSTER_SMOKE.json]
"""

from __future__ import annotations

import os

# Off-TPU by construction (the axon sitecustomize pins the platform —
# tools/soak.py measurement postmortem).
os.environ["JAX_PLATFORMS"] = "cpu"

import argparse  # noqa: E402
import sys  # noqa: E402
import tempfile  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from svoc_tpu.durability.faultspace import (  # noqa: E402
    FaultEvent,
    read_fired_log,
)
from svoc_tpu.utils.artifacts import atomic_write_json  # noqa: E402

N_REPLICAS = 3
N_CLAIMS = 6
TOTAL_STEPS = 10
ARRIVALS_PER_STEP = 8
KILL_REPLICA = "r1"
KILL_AT_STEP = 4

CLUSTER_POINTS = (
    "cluster.forward.pre_send",
    "cluster.migrate.pre_drain",
    "cluster.migrate.post_ship",
    "cluster.migrate.pre_adopt",
    "replica.kill",
)


def run_once(seed: int) -> dict:
    from svoc_tpu.cluster.scenario import run_cluster_scenario

    workdir = tempfile.mkdtemp(prefix="cluster-smoke-")
    result = run_cluster_scenario(
        workdir,
        seed=seed,
        n_replicas=N_REPLICAS,
        n_claims=N_CLAIMS,
        total_steps=TOTAL_STEPS,
        arrivals_per_step=ARRIVALS_PER_STEP,
        kill_replica=KILL_REPLICA,
        kill_at_step=KILL_AT_STEP,
        events=[
            FaultEvent(
                point="cluster.forward.pre_send", nth=7, action="error"
            )
        ],
    )
    result["workdir"] = workdir
    result["fired_log"] = read_fired_log(os.path.join(workdir, "fired.jsonl"))
    return result


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", default="CLUSTER_SMOKE.json")
    args = parser.parse_args()

    checks = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        checks.append({"check": name, "ok": bool(ok), "detail": detail})
        print(f"[{'PASS' if ok else 'FAIL'}] {name}" + (f" — {detail}" if detail else ""))

    first = run_once(args.seed)
    second = run_once(args.seed)

    # -- replay identity ----------------------------------------------------
    per_claim_identical = all(
        first["claims"][cid]["fingerprint"]
        == second["claims"][cid]["fingerprint"]
        for cid in first["claims"]
    )
    check(
        "per-claim fingerprints byte-identical across runs",
        per_claim_identical,
        f"{len(first['claims'])} claims",
    )
    check(
        "fleet fingerprint byte-identical across runs",
        first["fleet_fingerprint"] == second["fleet_fingerprint"],
        first["fleet_fingerprint"][:16],
    )

    # -- failover served ----------------------------------------------------
    check(
        "replica killed mid-run and failed over",
        first["kill"] is not None and first["failover"] is not None,
        f"killed {KILL_REPLICA} @ step {KILL_AT_STEP}",
    )
    owners = {cid: v["owner"] for cid, v in first["claims"].items()}
    check(
        "no claim still placed on the dead replica",
        all(owner != KILL_REPLICA for owner in owners.values()),
        str(owners),
    )
    moved = (first["failover"] or {}).get("claims", {})
    check(
        "every failed-over claim migrated with lineage continuity",
        bool(moved)
        and all(m.get("status") == "migrated" and m.get("continuity") for m in moved.values()),
        f"{sorted(moved)} -> {[m.get('target') for m in moved.values()]}",
    )
    check(
        "migrated claims serving on the new owners (chain still growing)",
        all(
            first["chain"][cid]["predictions"] > 0 for cid in moved
        ),
        str({cid: first["chain"][cid]["predictions"] for cid in sorted(moved)}),
    )
    check(
        "placement epoch advanced through the failover",
        first["epoch"] > N_REPLICAS,
        f"epoch {first['epoch']}",
    )

    # -- cluster-wide durability oracles ------------------------------------
    check(
        "zero duplicate txs across the shared chain logs",
        first["duplicate_txs"] == 0 and second["duplicate_txs"] == 0,
        f"{first['duplicate_txs']} + {second['duplicate_txs']}",
    )
    requests = first["requests"]
    check(
        "zero unaccounted admitted requests fleet-wide",
        requests["unaccounted"] == 0 and second["requests"]["unaccounted"] == 0,
        f"admitted={requests['admitted']:.0f} completed={requests['completed']:.0f} "
        f"dropped={requests['dropped']:.0f}",
    )
    check(
        "outage window shed typed, counted, journaled",
        first["cluster_counters"]["cluster_unavailable"] > 0,
        f"{first['cluster_counters']['cluster_unavailable']:.0f} sheds",
    )
    check(
        "stale-epoch probe answered with a typed redirect",
        any(p.get("status") == "redirect" for p in first["probes"]),
    )

    # -- fault-point coverage (durable fired log) ---------------------------
    fired = set(first["fired_log"]["fired"]) | set(second["fired_log"]["fired"])
    missing = [p for p in CLUSTER_POINTS if p not in fired]
    check(
        "all cluster fault points witnessed in the durable fired log",
        not missing,
        f"missing={missing}" if missing else f"{len(CLUSTER_POINTS)} points",
    )
    actions = first["fired_log"]["actions"] + second["fired_log"]["actions"]
    check(
        "injected forwarding fault executed and absorbed by retry",
        any(
            a["point"] == "cluster.forward.pre_send" and a["action"] == "error"
            for a in actions
        ),
    )

    ok = all(c["ok"] for c in checks)
    artifact = {
        "artifact": "cluster_smoke",
        "seed": args.seed,
        "config": {
            "n_replicas": N_REPLICAS,
            "n_claims": N_CLAIMS,
            "total_steps": TOTAL_STEPS,
            "arrivals_per_step": ARRIVALS_PER_STEP,
            "kill": {"replica": KILL_REPLICA, "at_step": KILL_AT_STEP},
        },
        "checks": checks,
        "requests": first["requests"],
        "cluster_counters": first["cluster_counters"],
        "claims": first["claims"],
        "fleet_fingerprint": first["fleet_fingerprint"],
        "epoch": first["epoch"],
        "ok": ok,
    }
    atomic_write_json(args.out, artifact)
    print(f"{'PASS' if ok else 'FAIL'}: cluster smoke -> {args.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
