#!/usr/bin/env python
"""Measure the axon backend's per-dispatch behavior on the real chip.

Round-2's flagship bench recorded a 256x128 roberta-base forward at
0.134 ms -- ~500x faster than the chip's bf16 peak allows -- strongly
suggesting the tunneled backend caches/elides repeated executions with
byte-identical inputs.  This probe establishes, with blocking timings:

1. trivial-op dispatch overhead (jitted add, scalar),
2. roberta-base forward latency with the SAME input buffer every call,
3. the same forward with a DIFFERENT (pre-staged) input buffer per call,
4. whether outputs differ across unique inputs (sanity).

Writes one JSON line to stdout and DISPATCH_PROBE.json.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def med_ms(fn, reps=20, warm=2):
    for _ in range(warm):
        jax.block_until_ready(fn())
    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        out.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(out)), [round(x, 3) for x in sorted(out)]


def main():
    result = {"backend": jax.default_backend()}

    # 1. trivial dispatch
    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(())
    jax.block_until_ready(f(x))
    m, samples = med_ms(lambda: f(x))
    result["trivial_dispatch_ms"] = round(m, 3)
    result["trivial_samples_ms"] = samples[:5] + samples[-3:]

    # 1b. trivial dispatch with unique input each call
    xs = [jnp.full((), float(i)) for i in range(20)]
    i = [0]

    def uniq_trivial():
        i[0] += 1
        return f(xs[i[0] % 20])

    m, _ = med_ms(uniq_trivial)
    result["trivial_unique_dispatch_ms"] = round(m, 3)

    # 2/3. roberta-base-shaped forward
    from svoc_tpu.models.configs import ROBERTA_GO_EMOTIONS
    from svoc_tpu.models.sentiment import SentimentPipeline

    B, S = 256, 128
    pipe = SentimentPipeline(
        cfg=ROBERTA_GO_EMOTIONS, seq_len=S, batch_size=B, tokenizer_name=None
    )
    fwd = pipe.forward_fn()
    rng = np.random.default_rng(0)
    n_uniq = 8
    ids_pool = [
        jax.device_put(jnp.asarray(rng.integers(10, 5000, (B, S)), jnp.int32))
        for _ in range(n_uniq)
    ]
    mask = jax.device_put(jnp.ones((B, S), jnp.int32))
    t0 = time.perf_counter()
    out0 = fwd(pipe.params, ids_pool[0], mask)
    jax.block_until_ready(out0)
    result["fwd_compile_s"] = round(time.perf_counter() - t0, 2)

    m, samples = med_ms(lambda: fwd(pipe.params, ids_pool[0], mask), reps=12)
    result["fwd_same_input_ms"] = round(m, 3)
    result["fwd_same_samples_ms"] = samples

    j = [0]

    def uniq_fwd():
        j[0] += 1
        return fwd(pipe.params, ids_pool[j[0] % n_uniq], mask)

    m, samples = med_ms(uniq_fwd, reps=12)
    result["fwd_unique_input_ms"] = round(m, 3)
    result["fwd_unique_samples_ms"] = samples

    outs = [np.asarray(fwd(pipe.params, ids_pool[k], mask)) for k in range(3)]
    result["outputs_differ"] = bool(
        not np.allclose(outs[0], outs[1]) and not np.allclose(outs[1], outs[2])
    )

    # implied FLOP/s at the unique-input latency
    flops = 256 * 128 * 12 * (
        2 * (4 * 768 * 768 + 2 * 768 * 3072) + 4 * 128 * 768
    )
    result["fwd_matmul_tflop"] = round(flops / 1e12, 3)
    result["implied_tflops_unique"] = round(
        flops / (result["fwd_unique_input_ms"] / 1e3) / 1e12, 1
    )
    result["implied_mfu_unique"] = round(result["implied_tflops_unique"] / 197.0, 3)

    line = json.dumps(result)
    print(line, flush=True)
    with open("DISPATCH_PROBE.json", "w") as fh:
        fh.write(line + "\n")


if __name__ == "__main__":
    main()
