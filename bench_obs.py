"""Cost-attribution plane overhead A/B (docs/OBSERVABILITY.md
§cost-attribution).

The question the artifact answers: does leaving the plane ON for every
request cost anything the serving tier can feel?  The seeded
``bench_serving.run_level`` scenario runs both arms — plane ``off``
vs plane ``on`` — at a fixed below-knee offered load, ``--repeats``
times each (interleaved off/on/off/on so drift hits both arms alike on
this 1-core container).  Latency percentiles are VIRTUAL time and
fingerprint-invariant, so the overhead metric is HOST time: every
measured ``tier.step()`` ``perf_counter`` duration, pooled across
repeats per arm, compared at p50/p99.  A mini plane-on knee sweep then
re-derives the saturation knee to show the serving shape is untouched.

Checks (gate): all fingerprints across BOTH arms and every repeat are
byte-identical (the plane is replay-invisible under load, not just in
the smoke), both arms measured real steps, and the knee survives.  The
p99 overhead itself is REPORTED, not gated — ``tools/decide_perf.py``
turns it into the ``cost_plane`` routing decision (on iff ≤ 5%).

A second **fleet arm** (docs/OBSERVABILITY.md §fleet-plane) A/Bs the
FLEET observability plane over the seeded 3-replica cluster scenario,
plane on vs off, interleaved per repeat.  The cluster scenario has no
per-step host sampler, so the measured unit is whole-run wall seconds
(hop sidecar writes + the per-step merge/SLO/anomaly pass are the only
delta); with few repeats the reported p99 is the max-of-repeats —
read it as a noise ceiling on this 1-core container, where the three
replicas already share one core and the arm is an honest null for
parallel-serving claims.  The gate again asserts fleet-fingerprint
identity across arms; the overhead is REPORTED against the same 5%
budget.

Usage::

    python bench_obs.py [--seed 0] [--qps 120] [--repeats 3]
                        [--fleet-repeats 7] [--out BENCH_OBS_r12.json]
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import argparse  # noqa: E402
import sys  # noqa: E402

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_serving import DEFAULT_QPS, find_knee, run_level  # noqa: E402

#: The ``cost_plane`` decision threshold (tools/decide_perf.py): the
#: plane defaults ON only when its measured p99 host step overhead is
#: within this fraction of the off arm.
OVERHEAD_BUDGET = 0.05


def run_arm(arm, qps, seed, repeats):
    """Pooled host-step samples + per-repeat fingerprints for one arm."""
    samples, fingerprints, records = [], [], []
    for rep in range(repeats):
        rec = run_level(qps, seed=seed, cost_plane=arm)
        host = rec.pop("host_step_ms")
        rec.pop("step_detail")
        samples.extend(host["samples_s"])
        fingerprints.append(rec["journal_fingerprint"])
        records.append(
            {
                "repeat": rep,
                "p50_host_ms": host["p50"],
                "p99_host_ms": host["p99"],
                "total_host_s": host["total_s"],
                "completed": rec["completed"],
                "journal_fingerprint": rec["journal_fingerprint"],
            }
        )
    return samples, fingerprints, records


FLEET_PLAN = dict(
    n_replicas=3, n_claims=3, total_steps=8, arrivals_per_step=6
)


def run_fleet_arms(seed, repeats):
    """Interleaved plane-off/plane-on cluster runs; per-run wall
    seconds (perf_counter around the whole scenario) + fleet
    fingerprints per arm."""
    import tempfile
    import time

    from svoc_tpu.cluster.scenario import run_cluster_scenario

    walls = {"off": [], "on": []}
    prints = {"off": [], "on": []}
    with tempfile.TemporaryDirectory(prefix="bench_obs_fleet_") as tmp:
        # Discarded warmup (same rationale as the serving arms).
        run_cluster_scenario(
            os.path.join(tmp, "warm"), seed, fleet_plane=False, **FLEET_PLAN
        )
        for rep in range(repeats):
            for arm, plane in (("off", False), ("on", True)):
                t0 = time.perf_counter()
                rec = run_cluster_scenario(
                    os.path.join(tmp, f"{arm}{rep}"), seed,
                    fleet_plane=plane, **FLEET_PLAN,
                )
                wall = time.perf_counter() - t0
                walls[arm].append(wall)
                prints[arm].append(rec["fleet_fingerprint"])
                print(
                    f"  fleet rep {rep} {arm:>3}: wall {wall:6.3f} s, "
                    f"fingerprint {rec['fleet_fingerprint'][:16]}"
                )
    stats = {}
    for arm in ("off", "on"):
        vals = walls[arm]
        stats[arm] = {
            "runs": len(vals),
            "wall_s": [round(v, 4) for v in vals],
            "median_wall_s": round(float(np.median(vals)), 4),
            "mean_wall_s": round(float(np.mean(vals)), 4),
            # Max-of-repeats: the honest "p99" a handful of whole-run
            # samples supports (docstring caveat).
            "p99_wall_s": round(float(np.max(vals)), 4),
        }
    return stats, prints


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--qps",
        type=float,
        default=120.0,
        help="fixed below-knee offered load for the A/B",
    )
    p.add_argument("--repeats", type=int, default=5)
    p.add_argument(
        "--fleet-repeats",
        type=int,
        default=7,
        help="per-arm repeats for the 3-replica fleet-plane A/B "
        "(whole-run wall seconds are noisy on a shared core — a "
        "handful of repeats is the difference between a noise "
        "artifact and a readable median)",
    )
    p.add_argument(
        "--knee-qps",
        default=",".join(str(q) for q in DEFAULT_QPS),
        help="plane-on knee sweep levels",
    )
    p.add_argument("--out", default="BENCH_OBS_r12.json")
    args = p.parse_args(argv)

    from svoc_tpu.utils.artifacts import atomic_write_json

    # One discarded run first: the process-level compile cost (jit
    # tracing on the first dispatches) would otherwise land entirely on
    # whichever arm runs first and swamp the A/B.
    run_level(args.qps, seed=args.seed, cost_plane="off")
    print("  warmup run discarded (process-level compiles paid)")

    # Interleave arms per repeat so slow host drift (thermal, page
    # cache) lands on both sides symmetrically.
    pooled = {"off": [], "on": []}
    prints = {"off": [], "on": []}
    repeats = {"off": [], "on": []}
    for rep in range(args.repeats):
        for arm in ("off", "on"):
            s, f, r = run_arm(arm, args.qps, args.seed, 1)
            pooled[arm].extend(s)
            prints[arm].extend(f)
            repeats[arm].extend(
                {**rec, "repeat": rep} for rec in r
            )
            print(
                f"  rep {rep} {arm:>3}: p99 host "
                f"{r[0]['p99_host_ms']:7.3f} ms, total "
                f"{r[0]['total_host_s']:6.3f} s, fingerprint "
                f"{f[0][:16]}"
            )

    arm_stats = {}
    for arm in ("off", "on"):
        vals = np.asarray(pooled[arm]) * 1e3  # samples are seconds
        # Per-arm p99 = MEDIAN of the per-repeat p99s: the pooled p99
        # is the top 1-2 samples of the pool — pure GC/scheduler noise
        # on this 1-core container — while the median-of-p99s tracks
        # the repeatable tail.
        rep_p99s = [r["p99_host_ms"] for r in repeats[arm]]
        arm_stats[arm] = {
            "steps": int(vals.size),
            "p50_host_ms": round(float(np.percentile(vals, 50)), 4),
            "p99_host_ms": round(float(np.median(rep_p99s)), 4),
            "p99_per_repeat_ms": rep_p99s,
            "mean_host_ms": round(float(np.mean(vals)), 4),
        }
    p99_off = arm_stats["off"]["p99_host_ms"]
    p99_on = arm_stats["on"]["p99_host_ms"]
    p50_off = arm_stats["off"]["p50_host_ms"]
    p50_on = arm_stats["on"]["p50_host_ms"]
    p99_overhead = (p99_on - p99_off) / p99_off if p99_off > 0 else None
    p50_overhead = (p50_on - p50_off) / p50_off if p50_off > 0 else None

    print("  knee sweep (plane on):")
    knee_levels = sorted(
        float(tok) for tok in args.knee_qps.split(",") if tok
    )
    knee_sweep = []
    for qps in knee_levels:
        rec = run_level(qps, seed=args.seed, cost_plane="on")
        rec.pop("step_detail")
        rec.pop("host_step_ms")
        knee_sweep.append(rec)
        print(
            f"    qps {qps:7.1f}: goodput {rec['goodput_qps']:7.1f}, "
            f"shed {rec['shed_rate']:6.1%}"
        )
    knee = find_knee(knee_sweep)

    print("  fleet-plane A/B (3-replica cluster scenario):")
    fleet_stats, fleet_prints = run_fleet_arms(
        args.seed, args.fleet_repeats
    )
    fleet_off = fleet_stats["off"]["median_wall_s"]
    fleet_on = fleet_stats["on"]["median_wall_s"]
    fleet_overhead = (
        (fleet_on - fleet_off) / fleet_off if fleet_off > 0 else None
    )

    checks = {
        # One fingerprint across BOTH arms and all repeats: replay
        # invisibility under open-loop load, per repeat, per arm.
        "fingerprints_identical_across_arms": (
            len(set(prints["off"]) | set(prints["on"])) == 1
        ),
        "both_arms_measured": all(
            s["steps"] > 0 and s["p99_host_ms"] > 0
            for s in arm_stats.values()
        ),
        "overhead_finite": p99_overhead is not None,
        "knee_inside_sweep": bool(
            knee and any(r["offered_qps"] > knee for r in knee_sweep)
        ),
        # Fleet-plane replay invisibility under the cluster scenario:
        # one fleet fingerprint across both arms and every repeat.
        "fleet_fingerprints_identical": (
            len(set(fleet_prints["off"]) | set(fleet_prints["on"])) == 1
        ),
        "fleet_both_arms_measured": all(
            s["runs"] > 0 and s["median_wall_s"] > 0
            for s in fleet_stats.values()
        ),
    }
    ok = all(checks.values())
    from bench import device_topology

    artifact = {
        "seed": args.seed,
        "qps": args.qps,
        "repeats": args.repeats,
        "device_topology": device_topology(),
        "overhead_budget": OVERHEAD_BUDGET,
        "p99_overhead": (
            round(p99_overhead, 4) if p99_overhead is not None else None
        ),
        "p50_overhead": (
            round(p50_overhead, 4) if p50_overhead is not None else None
        ),
        "within_budget": (
            p99_overhead is not None and p99_overhead <= OVERHEAD_BUDGET
        ),
        "arms": arm_stats,
        "arm_repeats": repeats,
        "journal_fingerprint": prints["off"][0],
        "knee_qps_plane_on": knee,
        "knee_sweep": knee_sweep,
        "fleet": {
            "plan": FLEET_PLAN,
            "repeats": args.fleet_repeats,
            "arms": fleet_stats,
            "median_overhead": (
                round(fleet_overhead, 4)
                if fleet_overhead is not None
                else None
            ),
            "within_budget": (
                fleet_overhead is not None
                and fleet_overhead <= OVERHEAD_BUDGET
            ),
            "fleet_fingerprint": fleet_prints["off"][0],
            "caveat": (
                "whole-run wall seconds on a 1-core host: the three "
                "replicas share one core, so the arm bounds plane "
                "bookkeeping cost and is an honest null for "
                "parallel-serving claims; p99 is max-of-repeats"
            ),
        },
        "checks": checks,
        "ok": ok,
    }
    atomic_write_json(args.out, artifact)
    for name, passed in checks.items():
        print(f"  {'PASS' if passed else 'FAIL'}  {name}")
    print(
        f"bench-obs {'OK' if ok else 'FAILED'}: p99 host step "
        f"{p99_off:.3f} -> {p99_on:.3f} ms "
        f"({p99_overhead:+.1%} overhead, budget {OVERHEAD_BUDGET:.0%}), "
        f"p50 {p50_overhead:+.1%}, knee (plane on) ~{knee:g} QPS, "
        f"fleet plane {fleet_off:.3f} -> {fleet_on:.3f} s median "
        f"({fleet_overhead:+.1%}) -> {args.out}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
